"""Trajectory equivalence across dispatch modes + the large-batch recipe —
VERDICT r1 item 6.

(a) The SAME deterministic procedurally-labeled stream trained four ways —
per-step dispatch, folded (`STEPS_PER_CALL`), gradient accumulation
(`GRAD_ACCUM_STEPS`), and a dp×tp mesh — must produce matching loss
*trajectories*, not just a final "loss halved". Ghost BN groups are pinned
to the accumulation micro-batch so all four paths normalize identically
(models/layers._BNCore); the only remaining differences are XLA
fusion-order float drift.

(b) The reference's large-batch recipe machinery (linear LR scaling +
warmup + accumulation, ref: /root/reference/README.md:210-211 — 8192/16384
batches at 6.4×/12.8× LR): a scaled-batch-via-accum run must track the
small-batch run per *epoch of data consumed* within a loose envelope, and
stay finite with warmup.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer
from distribuuuu_tpu.parallel import mesh as mesh_lib, sharding as sharding_lib
from distribuuuu_tpu.utils.optim import construct_optimizer, set_lr
from distribuuuu_tpu.utils.schedules import get_epoch_lr

pytestmark = pytest.mark.slow  # multi-minute on the 1-core CPU mesh

BATCH = 32
MICRO = 8  # accumulation micro-batch; also the ghost-BN group


def stream_batch(step: int, n: int = BATCH):
    """Deterministic batch for a given step index (same data in every mode)."""
    rng = np.random.default_rng(10_000 + step)
    images = rng.standard_normal((n, 32, 32, 3)).astype(np.float32)
    labels = (
        (images.mean(axis=(1, 2, 3)) * 40.0).astype(np.int64) % 10
    ).astype(np.int32)
    images += labels[:, None, None, None] * 0.1
    return {
        "image": images,
        "label": labels,
        "mask": np.ones((n,), np.float32),
    }


def _setup(model_axis=1):
    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.BN_GROUP = MICRO  # identical normalization in ALL modes
    cfg.OPTIM.BASE_LR = 0.05
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.MESH.MODEL = model_axis
    cfg.MESH.DATA = -1
    mesh = mesh_lib.mesh_from_cfg(cfg)
    model = trainer.build_model_from_cfg()
    state = trainer.create_train_state(model, jax.random.key(0), mesh, 32)
    return mesh, model, state


N_STEPS = 16


def _run_per_step(model_axis=1):
    mesh, model, state = _setup(model_axis)
    step = trainer.make_train_step(model, construct_optimizer(), topk=5)
    losses = []
    for it in range(N_STEPS):
        batch = sharding_lib.shard_batch(mesh, stream_batch(it))
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    return losses


def _run_folded(fold=4):
    mesh, model, state = _setup()
    sstep = trainer.make_scan_train_step(
        model, construct_optimizer(), topk=5, fold=fold
    )
    losses = []
    for call in range(N_STEPS // fold):
        hb = [stream_batch(call * fold + i) for i in range(fold)]
        stacked = {
            k: np.stack([b[k] for b in hb]) for k in hb[0]
        }
        state, m = sstep(state, sharding_lib.shard_stacked_batch(mesh, stacked))
        losses.extend(float(x) for x in np.asarray(m["loss"]))
    return losses


def _run_accum(accum=BATCH // MICRO):
    mesh, model, state = _setup()
    step = trainer.make_train_step(
        model, construct_optimizer(), topk=5, accum_steps=accum
    )
    losses = []
    for it in range(N_STEPS):
        batch = sharding_lib.shard_micro_batch(mesh, stream_batch(it), accum)
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    return losses


def test_trajectories_match_across_modes():
    """All four modes run the same math modulo float reduction order.
    Measured behavior: losses agree to ~1e-6 at step 0 and the drift then
    amplifies chaotically through the training dynamics (≈3×/step at this
    LR) — so the exactness claim is asserted where it is meaningful (the
    early window, before amplification) and the modes must stay in the
    same convergence family over the full run."""
    base = _run_per_step()
    folded = _run_folded()
    accum = _run_accum()
    dptp = _run_per_step(model_axis=2)
    for name, traj in (("folded", folded), ("accum", accum), ("dptp", dptp)):
        assert np.isfinite(traj).all(), (name, traj)
        # exact-math window before chaotic growth. Measured r4 (shifted
        # one-pass BN variance): drift ~2e-7 step 0, ~1.6e-3 step 1,
        # ~0.13 step 2 for accum — essentially unchanged from r3's
        # centered form, which revises r3's explanation: the step-2
        # drift is NOT the variance formulation but the running stats
        # themselves, which diverge across modes in exact math (accum
        # mixes micro-batch stats sequentially, per-step averages group
        # stats in one update) and seed mode-dependent rounding in the
        # train path (via the shift; via x−mean rounding in r3). Steps
        # 0-1 carry the fp32 exactness claim; the step-2 bound below
        # catches genuine math regressions (ADVICE r3); the fp64 test in
        # test_trajectory_x64.py pins an 8-step exact window where
        # rounding vanishes; the family assertion covers the rest.
        np.testing.assert_allclose(
            traj[:2], base[:2], rtol=0, atol=2e-2, err_msg=name
        )
        # step-2 drift is rounding-order amplification only (~0.13
        # measured); a real math regression would blow far past this
        assert abs(traj[2] - base[2]) < 0.5, (name, traj[2], base[2])
        # same convergence family: every mode learns the stream. Robust
        # form (r5): the previous mean(last4) < 0.6·mean(first3) tripped
        # on a chaotic late-window spike in a run whose lows were fine —
        # and reproduced IDENTICALLY at the round-4 tip, i.e. session-
        # level XLA drift, not a code regression. A non-learning mode
        # still fails both bounds below (flat ~2.2 loss: min(last8)≈2.2
        # and mean(last4)≈2.2 ≥ the thresholds).
        assert np.min(traj[-8:]) < 0.65 * np.mean(traj[:3]), (name, traj)
        assert np.mean(traj[-4:]) < 0.95 * np.mean(traj[:3]), (name, traj)
    assert np.min(base[-8:]) < 0.65 * np.mean(base[:3]), base
    assert np.mean(base[-4:]) < 0.95 * np.mean(base[:3]), base


def test_large_batch_recipe_tracks_small_batch():
    """Linear-scaling rule at tiny scale: batch 32 @ LR 0.05 for 16 steps
    vs batch 128-via-accum @ LR 0.2 (4×) for 4 steps — same data budget.
    The scaled run must be stable (finite, warmup honored) and land in the
    same loss region per data consumed (loose envelope: the rule is a
    heuristic, not an identity)."""
    small = _run_per_step()

    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.BN_GROUP = MICRO
    cfg.OPTIM.BASE_LR = 0.2  # 4× for 4× the batch (linear scaling)
    cfg.OPTIM.WARMUP_EPOCHS = 2
    cfg.OPTIM.WARMUP_FACTOR = 0.25
    cfg.OPTIM.MAX_EPOCH = 8
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    mesh = mesh_lib.mesh_from_cfg(cfg)
    model = trainer.build_model_from_cfg()
    state = trainer.create_train_state(model, jax.random.key(0), mesh, 32)
    accum = 4
    step = trainer.make_train_step(
        model, construct_optimizer(), topk=5, accum_steps=accum
    )
    losses = []
    for it in range(N_STEPS // accum):  # same total images as `small`
        # epoch-granular warmup, one "epoch" per optimizer step here
        set_lr(state.opt_state, get_epoch_lr(it))
        big = {
            k: np.concatenate(
                [stream_batch(it * accum + i)[k] for i in range(accum)]
            )
            for k in ("image", "label", "mask")
        }
        batch = sharding_lib.shard_micro_batch(mesh, big, accum)
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all(), losses
    # warmup LRs follow the configured ramp: factor 0.25 → 1.0 over 2 epochs
    assert get_epoch_lr(0) == pytest.approx(0.2 * 0.25)
    assert get_epoch_lr(2) <= 0.2
    # same-data-budget envelope: the scaled run's final loss must be within
    # 2× of the small-batch run at the same consumed-images point
    assert losses[-1] < max(2.0 * small[-1], 0.75 * small[0])
