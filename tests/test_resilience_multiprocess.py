"""Hard-crash recovery across real OS processes (slow tier).

SIGKILL — unlike the SIGTERM drills in test_multiprocess_e2e.py — gives
the dying rank NO grace window: no preempt save, no drain, nothing. The
recovery story is entirely the restart's: the surviving checkpoint on
disk must verify intact and the next launch must resume from it cleanly.
This drill kills one of two ranks mid-epoch-1 (deterministically, via
``FAULTS.KILL_RANK/KILL_EPOCH/KILL_AT_BATCH`` — the worker SIGKILLs
itself at a batch boundary), reaps the wedged survivor the way a fleet
scheduler would, and asserts a full-group restart completes the run from
``ckpt_ep_000`` with no corrupt checkpoint ever selected.
"""

import os
import re
import signal
import subprocess
import sys
import time

import pytest

import test_multiprocess_e2e as mp

REPO = mp.REPO

WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("DTPU_TEST_NDEV", "2")
).strip()
import jax
jax.config.update("jax_platforms", "cpu")

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer

out_dir = sys.argv[1]
config.reset_cfg()
cfg.MODEL.ARCH = "resnet18"
cfg.MODEL.NUM_CLASSES = 10
cfg.MODEL.DUMMY_INPUT = True
cfg.OPTIM.MAX_EPOCH = 2
cfg.TRAIN.BATCH_SIZE = 2
cfg.TRAIN.IM_SIZE = 32
cfg.TRAIN.PRINT_FREQ = 4
cfg.TEST.BATCH_SIZE = 4
cfg.TEST.IM_SIZE = 32
cfg.RNG_SEED = 1
cfg.DEVICE.COMPUTE_DTYPE = "float32"
cfg.OUT_DIR = out_dir
if len(sys.argv) > 2:
    cfg.merge_from_list(sys.argv[2:])
best = trainer.train_model()
print(f"WORKER_DONE rank={jax.process_index()} best={best:.3f}", flush=True)
"""


@pytest.mark.slow
def test_sigkilled_rank_recovers_on_restart(tmp_path):
    out_dir = str(tmp_path / "run")
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    ckpt_dir = os.path.join(out_dir, "checkpoints")

    # ---- run 1: rank 1 SIGKILLs itself at epoch 1, batch 2 (after the
    # collective ckpt_ep_000 save committed) ----
    kill_args = (
        "FAULTS.ENABLED", "True", "FAULTS.KILL_RANK", "1",
        "FAULTS.KILL_EPOCH", "1", "FAULTS.KILL_AT_BATCH", "2",
    )
    procs, logs = mp._launch_group(
        tmp_path, script, (out_dir, *kill_args), nprocs=2, ndev=2,
        log_name=lambda rank, port: f"kill{rank}_{port}.log",
    )
    procs[1].wait(timeout=900)
    assert procs[1].returncode == -signal.SIGKILL, procs[1].returncode
    # the survivor is now wedged in (or erroring out of) a collective with
    # a dead peer; give it a moment to die on its own, then reap it the
    # way a fleet scheduler reaps a broken group
    deadline = time.time() + 30
    while time.time() < deadline and procs[0].poll() is None:
        time.sleep(1.0)
    if procs[0].poll() is None:
        procs[0].kill()
        procs[0].wait(timeout=60)
    for log in logs:
        log.close()

    names = sorted(os.listdir(ckpt_dir))
    assert "ckpt_ep_000" in names, names  # epoch 0 committed before the kill
    assert not any(n.startswith("ckpt_ep_001") for n in names), names

    # ---- run 2: full-group restart, no faults — must resume and finish ----
    procs, logs = mp._launch_group(
        tmp_path, script, (out_dir,), nprocs=2, ndev=2,
        log_name=lambda rank, port: f"restart{rank}_{port}.log",
    )
    outs = []
    for p, log in zip(procs, logs):
        p.wait(timeout=900)
        log.seek(0)
        outs.append(log.read())
        log.close()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
        assert "WORKER_DONE" in out, out[-2000:]
    assert re.search(r"resumed from .*ckpt_ep_000", outs[0]), outs[0][-2000:]
    names = sorted(os.listdir(ckpt_dir))
    assert {"best", "ckpt_ep_000", "ckpt_ep_001"} <= set(names), names
    # the committed save was intact — nothing should have been quarantined
    assert not any(".corrupt" in n for n in names), names
