"""TRAIN.REMAT — stage 1-2 rematerialization (models/resnet.py, the
remat-for-traffic roofline lever, VERDICT r5 #3): ``nn.remat`` changes
only what is stored vs recomputed for the backward, never the math or the
param tree, so the train step must be equivalent with the knob on or off.
The A/B throughput preset is ``tools/ab_bench.py --preset remat``.
"""

import jax
import numpy as np
import pytest

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg


def _run_steps(remat: bool, hb, n_steps: int = 2):
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.parallel import mesh as mesh_lib, sharding as sharding_lib
    from distribuuuu_tpu.utils.optim import construct_optimizer

    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.TRAIN.REMAT = remat
    mesh = mesh_lib.build_mesh()
    model = trainer.build_model_from_cfg()
    state = trainer.create_train_state(model, jax.random.key(0), mesh, 32)
    step = trainer.make_train_step(model, construct_optimizer(), 5)
    m = None
    for _ in range(n_steps):
        state, m = step(state, sharding_lib.shard_batch(mesh, hb))
    return jax.device_get(state.params), float(m["loss"])


@pytest.mark.slow  # 35s: remat on/off A/B steps; tier-1 budget (ISSUE 18)
def test_remat_step_equivalence():
    """Same init, same batches ⇒ same loss and same updated params with
    and without stage 1-2 rematerialization."""
    rng = np.random.default_rng(0)
    hb = {
        "image": rng.standard_normal((8, 32, 32, 3)).astype(np.float32),
        "label": rng.integers(0, 10, size=(8,)).astype(np.int32),
        "mask": np.ones((8,), np.float32),
    }
    params_plain, loss_plain = _run_steps(False, hb)
    params_remat, loss_remat = _run_steps(True, hb)
    assert loss_remat == pytest.approx(loss_plain, rel=1e-6)
    # identical param TREE (remat is a lifted transform — same names,
    # same shapes: checkpoints interchange) and matching values. The
    # forward is bitwise-identical; the UPDATED params carry ~1e-7 float
    # drift because remat rebuilds the backward graph (recompute instead
    # of reuse), so XLA reassociates its reductions — the same drift
    # class the scan-vs-per-step equivalence tests document.
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-3, atol=5e-6
        ),
        params_plain, params_remat,
    )


def test_remat_refused_outside_resnet_family():
    """The knob must refuse archs it does not touch rather than silently
    measuring an unchanged step."""
    from distribuuuu_tpu import trainer

    config.reset_cfg()
    cfg.MODEL.ARCH = "vit_tiny"
    cfg.TRAIN.REMAT = True
    with pytest.raises(ValueError, match="TRAIN.REMAT"):
        trainer.build_model_from_cfg()
