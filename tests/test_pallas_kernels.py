"""The kernel tier's exactness, selection, and composition pins (ISSUE 13).

Every kernel in ops/pallas/ runs here in interpret mode (the tier-1 CPU
story — same pallas_call the TPU lowers) against its XLA reference:

* fused optimizer update — BIT-exact jit-vs-jit for SGD-momentum (fp32
  and the bf16-momentum configuration) and AdamW, including the optax
  state structure and counters;
* fused conv epilogue — pinned tolerance (the fused path keeps the fp32
  accumulator into the affine; the reference rounds to the compute
  dtype first), with the param tree pinned compute-path-independent;
* fused decode attention — pinned tolerance vs the dense softmax, and
  logit-equivalence through the real GPTDecoder on a real GPT param
  tree, plus token-identical end-to-end generation;
* ZeRO shard-compatibility — updating a shard ≡ slicing the unsharded
  update (the elementwise-commute proof the partition layer's layouts
  rely on), plus the fused update running under the real ZeRO-1 lowering;
* selection discipline — KERNELS.* validation refusals with their
  arithmetic, kernel.select/kernel.fallback telemetry, warn-once
  fallback that stays correct, and the trajectory pin
  (KERNELS.*=pallas training ≡ xla within pinned tolerance);
* the bench-index pin — BENCH_r09's kernel_* series must never clobber
  the resnet50 img/s regression reference (the PR 8 lesson).
"""

from __future__ import annotations

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.ops import pallas as tier
from distribuuuu_tpu.ops.pallas import conv_epilogue as ce
from distribuuuu_tpu.ops.pallas import decode_attn as da
from distribuuuu_tpu.ops.pallas import opt_update as ou

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


@pytest.fixture(autouse=True)
def _fresh_tier():
    tier.reset_selection()
    yield
    tier.reset_selection()


def _tree_bit_equal(a, b):
    return all(jax.tree.leaves(jax.tree.map(
        lambda x, y: bool((x == y).all()), a, b
    )))


def _params(rng, dtype=jnp.float32):
    # deliberately awkward shapes: lane-unaligned, tiny, multi-block
    return {
        "w": jnp.asarray(rng.standard_normal((37, 13)), dtype),
        "b": jnp.asarray(rng.standard_normal((5,)), dtype),
        "big": jnp.asarray(rng.standard_normal((700_000,)), dtype),
    }


# ------------------------------------------------------ fused opt update


@pytest.mark.parametrize("mom_dtype", ["float32", "bfloat16"])
def test_fused_sgd_bit_exact_vs_optax(mom_dtype):
    from distribuuuu_tpu.utils.optim import construct_optimizer

    cfg.defrost()
    cfg.OPTIM.MOMENTUM_DTYPE = mom_dtype
    rng = np.random.default_rng(0)
    params = _params(rng)
    grads = jax.tree.map(lambda x: x * 0.1, params)
    opt = construct_optimizer()
    st = opt.init(params)

    @jax.jit
    def ref(p, g, s):
        u, s2 = opt.update(g, s, p)
        return optax.apply_updates(p, u), s2

    @jax.jit
    def fused(p, g, s):
        return ou.fused_optimizer_update(
            p, g, s, kind="sgd", wd=float(cfg.OPTIM.WEIGHT_DECAY),
            mom=float(cfg.OPTIM.MOMENTUM),
            nesterov=bool(cfg.OPTIM.NESTEROV), b1=0.9, b2=0.999,
            eps=1e-8, interpret=True,
        )

    p1, s1 = params, st
    p2, s2 = params, st
    for _ in range(2):  # two steps: the trace feeds back
        p1, s1 = ref(p1, grads, s1)
        p2, s2 = fused(p2, grads, s2)
    assert _tree_bit_equal(p1, p2)
    assert _tree_bit_equal(s1.inner_state[1][0].trace,
                           s2.inner_state[1][0].trace)
    if mom_dtype == "bfloat16":
        assert s2.inner_state[1][0].trace["w"].dtype == jnp.bfloat16
    assert int(s1.count) == int(s2.count)
    assert (jax.tree_util.tree_structure(s1)
            == jax.tree_util.tree_structure(s2))


def test_fused_adamw_bit_exact_vs_optax():
    from distribuuuu_tpu.utils.optim import construct_optimizer

    cfg.defrost()
    cfg.OPTIM.OPTIMIZER = "adamw"
    rng = np.random.default_rng(1)
    params = _params(rng)
    grads = jax.tree.map(lambda x: x * 0.03, params)
    opt = construct_optimizer()
    st = opt.init(params)

    @jax.jit
    def ref(p, g, s):
        u, s2 = opt.update(g, s, p)
        return optax.apply_updates(p, u), s2

    @jax.jit
    def fused(p, g, s):
        return ou.fused_optimizer_update(
            p, g, s, kind="adamw", wd=float(cfg.OPTIM.WEIGHT_DECAY),
            mom=0.9, nesterov=True, b1=float(cfg.OPTIM.BETA1),
            b2=float(cfg.OPTIM.BETA2), eps=1e-8, interpret=True,
        )

    p1, s1 = params, st
    p2, s2 = params, st
    for _ in range(3):  # bias correction moves with the count
        p1, s1 = ref(p1, grads, s1)
        p2, s2 = fused(p2, grads, s2)
    assert _tree_bit_equal(p1, p2)
    adam1, _ = ou._find_state(s1.inner_state, "mu")
    adam2, _ = ou._find_state(s2.inner_state, "mu")
    assert _tree_bit_equal(adam1.mu, adam2.mu)
    assert _tree_bit_equal(adam1.nu, adam2.nu)
    assert int(adam1.count) == int(adam2.count) == 3
    assert (jax.tree_util.tree_structure(s1)
            == jax.tree_util.tree_structure(s2))


def test_fused_sgd_without_momentum():
    cfg.defrost()
    cfg.OPTIM.MOMENTUM = 0.0
    from distribuuuu_tpu.utils.optim import construct_optimizer

    rng = np.random.default_rng(2)
    params = {"w": jnp.asarray(rng.standard_normal((9, 11)), jnp.float32)}
    grads = jax.tree.map(lambda x: x * 0.1, params)
    opt = construct_optimizer()
    st = opt.init(params)

    @jax.jit
    def ref(p, g, s):
        u, s2 = opt.update(g, s, p)
        return optax.apply_updates(p, u), s2

    @jax.jit
    def fused(p, g, s):
        return ou.fused_optimizer_update(
            p, g, s, kind="sgd", wd=float(cfg.OPTIM.WEIGHT_DECAY),
            mom=0.0, nesterov=True, b1=0.9, b2=0.999, eps=1e-8,
            interpret=True,
        )

    p1, _ = ref(params, grads, st)
    p2, _ = fused(params, grads, st)
    assert _tree_bit_equal(p1, p2)


def test_zero_sharded_update_equals_unsharded_then_shard():
    """The partition layer's shard-compat contract: the fused update is
    elementwise per leaf, so updating a ZeRO shard must equal slicing
    the unsharded update — exactly, per shard, for params AND moments."""
    rng = np.random.default_rng(3)
    n, shards = 4096, 8
    p = jnp.asarray(rng.standard_normal(n), jnp.float32)
    g = jnp.asarray(rng.standard_normal(n), jnp.float32)
    t = jnp.asarray(rng.standard_normal(n), jnp.float32)
    lr = jnp.float32(0.1)
    kw = dict(wd=5e-5, mom=0.9, nesterov=True, interpret=True)
    full_p, full_t = jax.jit(
        lambda p, g, t: ou.sgd_leaf(p, g, t, lr, **kw)
    )(p, g, t)
    per = n // shards
    for i in range(shards):
        sl = slice(i * per, (i + 1) * per)
        sp, st_ = jax.jit(
            lambda p, g, t: ou.sgd_leaf(p, g, t, lr, **kw)
        )(p[sl], g[sl], t[sl])
        assert bool((sp == full_p[sl]).all())
        assert bool((st_ == full_t[sl]).all())


@pytest.mark.slow  # 39s: real ZeRO-1+3 lowerings; tier-1 budget (ISSUE 18)
def test_fused_update_under_real_zero_lowering():
    """KERNELS.OPT_UPDATE=pallas composed with the partition layer's
    ZeRO-1 layout on the 8-device mesh: the trajectory must match the
    XLA reference path's within the pinned tolerance. Since r16 the
    fused update lowers PER-SHARD through shard_map
    (opt_update.per_shard_update) — both arms consume the same
    reduce-scattered grads; tests/test_zero_overlap.py adds the ZeRO-3
    twin and pins the census stays gather-once."""
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.parallel import mesh as mesh_lib, sharding
    from distribuuuu_tpu.parallel.partition import topology as topo_lib

    def run_two_steps():
        mesh = mesh_lib.build_mesh()
        topo = topo_lib.from_cfg(cfg)
        model = trainer.build_model_from_cfg(topo)
        from distribuuuu_tpu.parallel.partition import lowering
        from distribuuuu_tpu.utils.optim import construct_optimizer

        lowered = lowering.lower(
            model, construct_optimizer(), topk=2, mesh=mesh,
            topology=topo, im_size=16,
        )
        state = lowered.init_state(jax.random.key(0), 16)
        rng = np.random.default_rng(0)
        batch = sharding.shard_batch(mesh, {
            "image": rng.standard_normal((8, 16, 16, 3)).astype(np.float32),
            "label": rng.integers(0, 4, (8,)).astype(np.int32),
            "mask": np.ones((8,), np.float32),
        })
        for _ in range(2):
            state, metrics = lowered.train_step(state, batch)
        return jax.device_get(state.params), jax.device_get(metrics)

    cfg.defrost()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 4
    cfg.MESH.ZERO = 1
    # both arms consume the same reduce-scattered grads (per-shard
    # lowering), but XLA fuses the in-step optax chain with different
    # FMA contraction than the shard_map'd kernel region — ulp-level
    # drift that a reference-recipe LR of 0.1 amplifies chaotically
    # through BN+relu within two steps; the pin is layout composition,
    # not chaos
    cfg.OPTIM.BASE_LR = 0.001
    ref_params, ref_metrics = run_two_steps()
    cfg.defrost()
    cfg.KERNELS.OPT_UPDATE = "pallas"
    pal_params, pal_metrics = run_two_steps()
    diffs = jax.tree.leaves(jax.tree.map(
        lambda a, b: float(np.abs(np.asarray(a, np.float32)
                                  - np.asarray(b, np.float32)).max()),
        ref_params, pal_params,
    ))
    assert max(diffs) <= 5e-6, max(diffs)
    assert np.isclose(float(ref_metrics["loss"]), float(pal_metrics["loss"]),
                      rtol=1e-5)


@pytest.mark.slow  # 29s: two full toy train runs; tier-1 budget (ISSUE 18)
def test_trajectory_pin_pallas_vs_xla_training():
    """The tier's headline contract: a KERNELS.OPT_UPDATE=pallas training
    run tracks the xla reference within the pinned tolerance (the only
    drift source is XLA fusing the in-step reference chain with
    different FMA contraction than the standalone jit — ~1 ulp/step)."""
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.parallel.partition import lowering
    from distribuuuu_tpu.utils.optim import construct_optimizer

    def run(n_steps=3):
        model = trainer.build_model_from_cfg()
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((4, 24, 24, 3)), jnp.float32)
        y = jnp.asarray(rng.integers(0, 8, (4,)), jnp.int32)
        v = model.init(jax.random.key(0), x, train=True)
        state = lowering.TrainState(
            params=v["params"], batch_stats=v.get("batch_stats", {}),
            opt_state=construct_optimizer().init(v["params"]),
            step=jnp.int32(0), key=jax.random.key(1),
        )
        step = lowering.make_train_step(
            model, construct_optimizer(), topk=2
        )
        for _ in range(n_steps):
            state, _ = step(state, {"image": x, "label": y})
        return jax.device_get(state.params)

    cfg.defrost()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 8
    ref = run()
    cfg.defrost()
    cfg.KERNELS.OPT_UPDATE = "pallas"
    pal = run()
    diffs = jax.tree.leaves(jax.tree.map(
        lambda a, b: float(np.abs(np.asarray(a, np.float32)
                                  - np.asarray(b, np.float32)).max()),
        ref, pal,
    ))
    assert max(diffs) <= 5e-6, max(diffs)


# ------------------------------------------------------- conv epilogue


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_conv_epilogue_tolerance(dtype):
    rng = np.random.default_rng(4)
    B, H, W, cin, cout = 2, 5, 5, 48, 96
    x = jnp.asarray(rng.standard_normal((B, H, W, cin)), dtype)
    k = jnp.asarray(rng.standard_normal((1, 1, cin, cout)) * 0.1,
                    jnp.float32)
    mean = jnp.asarray(rng.standard_normal(cout) * 0.2, jnp.float32)
    var = jnp.asarray(rng.random(cout) + 0.3, jnp.float32)
    scale = jnp.asarray(rng.standard_normal(cout) * 0.3 + 1.0, jnp.float32)
    bias = jnp.asarray(rng.standard_normal(cout) * 0.2, jnp.float32)
    inv = jax.lax.rsqrt(var + 1e-5) * scale
    a, c = inv, bias - mean * inv

    @jax.jit
    def ref(x):
        o = jax.lax.conv_general_dilated(
            x, k.astype(dtype), (1, 1), [(0, 0), (0, 0)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        y = (o.astype(jnp.float32) - mean) * inv + bias
        return jnp.maximum(y, 0.0).astype(dtype)

    @jax.jit
    def fused(x):
        return ce.conv1x1_bn_act(x, k.astype(dtype), a, c, "relu",
                                 interpret=True)

    r, f = ref(x), fused(x)
    tol = 1e-5 if dtype == jnp.float32 else 0.0625  # pinned per dtype
    d = float(jnp.abs(r.astype(jnp.float32) - f.astype(jnp.float32)).max())
    assert d <= tol, d


def test_conv_epilogue_through_convbn_and_param_tree():
    import flax.linen as nn

    from distribuuuu_tpu.models.layers import ConvBN

    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 32)), jnp.float32)
    m = ConvBN(64, (1, 1), 1, act=nn.relu)
    v = m.init(jax.random.key(0), x, train=False)
    # non-default BN stats so the affine folding is actually exercised
    v = {
        "params": v["params"],
        "batch_stats": jax.tree.map(
            lambda s: s + jnp.asarray(
                rng.random(s.shape) * 0.3, s.dtype
            ),
            v["batch_stats"],
        ),
    }
    ref = jax.jit(lambda v, x: m.apply(v, x, train=False))(v, x)
    cfg.defrost()
    cfg.KERNELS.CONV_EPILOGUE = "pallas"
    v2 = m.init(jax.random.key(0), x, train=False)
    assert (jax.tree_util.tree_structure(v2)
            == jax.tree_util.tree_structure(v))  # compute-path-independent
    fused = jax.jit(lambda v, x: m.apply(v, x, train=False))(v, x)
    d = float(jnp.abs(ref.astype(jnp.float32)
                      - fused.astype(jnp.float32)).max())
    assert d <= 0.0625, d


def test_conv_epilogue_efficientnet_eval_and_fallback_warns_once():
    """EfficientNet eval under forced pallas: the pointwise chains fuse,
    every non-qualifying site (3×3 stem, depthwise) falls back with ONE
    warning per distinct reason — never one per call site — and the
    logits stay within tolerance."""
    from distribuuuu_tpu.models.efficientnet import EfficientNet

    m = EfficientNet(blocks=((1, 16, 1, 1, 3), (6, 24, 1, 2, 3)),
                     stem_ch=8, head_ch=64, num_classes=4)
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.standard_normal((2, 32, 32, 3)), jnp.float32)
    v = m.init(jax.random.key(0), x, train=False)
    ref = jax.jit(lambda v, x: m.apply(v, x, train=False))(v, x)
    cfg.defrost()
    cfg.KERNELS.CONV_EPILOGUE = "pallas"
    v2 = m.init(jax.random.key(0), x, train=False)
    assert (jax.tree_util.tree_structure(v2)
            == jax.tree_util.tree_structure(v))
    fused = jax.jit(lambda v, x: m.apply(v, x, train=False))(v, x)
    d = float(jnp.abs(ref.astype(jnp.float32)
                      - fused.astype(jnp.float32)).max())
    assert d <= 0.25, d  # bf16 logits through a different rounding path
    # the warn-once registry holds one entry per (op, reason) — the 3×3
    # stem and the grouped depthwise are distinct reasons; dozens of
    # call sites, but never dozens of warnings (the repo logger does not
    # propagate, so the dedup set IS the observable)
    fallback_reasons = {r for (op, r) in tier._warned
                        if op == "conv_epilogue"}
    assert 1 <= len(fallback_reasons) <= 3


def test_conv_epilogue_training_never_fuses():
    """The fused path is eval-only: a train=True forward under forced
    pallas must keep real batch-stat BN (stats update, raw conv out)."""
    import flax.linen as nn

    from distribuuuu_tpu.models.layers import ConvBN

    cfg.defrost()
    cfg.KERNELS.CONV_EPILOGUE = "pallas"
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((4, 4, 4, 16)), jnp.float32)
    m = ConvBN(32, (1, 1), 1, act=nn.relu)
    v = m.init(jax.random.key(0), x, train=True)
    y, mutated = m.apply(v, x, train=True, mutable=["batch_stats"])
    # stats moved off their init: the batch path ran, not the affine
    var = jax.tree.leaves(mutated["batch_stats"])
    assert any(
        float(jnp.abs(s.astype(jnp.float32)
                      - jnp.asarray(i, jnp.float32)).max()) > 0
        for s, i in zip(var, [0.0, 1.0])
    )


# -------------------------------------------------------- decode attn


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_decode_attn_tolerance(dtype):
    rng = np.random.default_rng(8)
    B, H, C, D = 3, 2, 256, 32
    q = jnp.asarray(rng.standard_normal((B, H, D)), dtype)
    ck = jnp.asarray(rng.standard_normal((B, H, C, D)), dtype)
    cv = jnp.asarray(rng.standard_normal((B, H, C, D)), dtype)
    lens = jnp.asarray([0, 100, C - 1], jnp.int32)  # fresh/mid/full rows
    sc = D ** -0.5

    @jax.jit
    def dense(q, ck, cv):
        s = jnp.einsum("bhd,bhcd->bhc", q.astype(jnp.float32),
                       ck.astype(jnp.float32)) * sc
        vis = jnp.arange(C)[None, None, :] <= lens[:, None, None]
        s = jnp.where(vis, s, jnp.float32(-1e30))
        w = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhc,bhcd->bhd", w, cv.astype(jnp.float32))

    @jax.jit
    def fused(q, ck, cv):
        return da.decode_attention(q, ck, cv, lens, scale=sc,
                                   interpret=True)

    d = float(jnp.abs(dense(q, ck, cv) - fused(q, ck, cv)).max())
    assert d <= 1e-5, d  # fp32 online-softmax summation order


def test_decode_attn_matches_cached_attention_on_gpt_params():
    """Logit-equivalence through the REAL decoder: GPTDecoder applied to
    a real GPT param tree, xla vs forced-pallas decode step."""
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.lm import generate as gen

    cfg.defrost()
    cfg.MODEL.ARCH = "gpt_nano"
    cfg.MODEL.NUM_CLASSES = 320
    cfg.LM.SEQ_LEN = 64
    model = trainer.build_model_from_cfg()
    variables = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32), train=False
    )
    dec = gen.decoder_for(model)
    B, C = 2, 64
    hh, dh = model.num_heads, model.dim // model.num_heads
    rng = np.random.default_rng(9)
    cache = {
        "k": jnp.asarray(
            rng.standard_normal((model.depth, B, hh, C, dh)) * 0.3,
            model.dtype),
        "v": jnp.asarray(
            rng.standard_normal((model.depth, B, hh, C, dh)) * 0.3,
            model.dtype),
    }
    lens = jnp.asarray([4, 40], jnp.int32)
    toks = jnp.asarray([[7], [200]], jnp.int32)
    run = jax.jit(lambda v, t, l, c: dec.apply(v, t, l, c))
    lo_ref, cache_ref = run(variables, toks, lens, cache)
    cfg.defrost()
    cfg.KERNELS.DECODE_ATTN = "pallas"
    cfg.KERNELS.DECODE_BLOCK = 32
    lo_pal, cache_pal = run(variables, toks, lens, cache)
    d = float(jnp.abs(lo_ref.astype(jnp.float32)
                      - lo_pal.astype(jnp.float32)).max())
    assert d <= 0.05, d  # bf16 activations through the block softmax
    assert _tree_bit_equal(cache_ref, cache_pal)  # cache write untouched


@pytest.mark.parametrize("impl", ["xla", "pallas"])
def test_generate_engine_tokens_identical(impl, tmp_path):
    """End-to-end: greedy generation must produce the SAME tokens with
    the fused decode kernel as with the dense reference."""
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.lm.generate import GenerateEngine

    cfg.defrost()
    cfg.MODEL.ARCH = "gpt_nano"
    cfg.MODEL.NUM_CLASSES = 320
    cfg.LM.SEQ_LEN = 64
    cfg.KERNELS.DECODE_ATTN = impl
    cfg.KERNELS.DECODE_BLOCK = 64
    model = trainer.build_model_from_cfg()
    variables = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32), train=False
    )
    eng = GenerateEngine(
        model, variables, max_new_tokens=6, prompt_len=8,
        batch_tiles=[2], cache_tiles=[64], eos_id=-1,
    )
    with eng:
        toks = eng.submit([1, 2, 3, 4]).result(timeout=60)
    assert len(toks) == 6
    # stash per-impl results on the module for the cross-impl compare
    key = "_gen_tokens"
    store = globals().setdefault(key, {})
    store[impl] = toks
    if len(store) == 2:
        assert store["xla"] == store["pallas"], store


# --------------------------------------------- selection + validation


def test_kernels_cfg_refusals():
    cfg.defrost()
    cfg.KERNELS.OPT_UPDATE = "mosaic"
    with pytest.raises(ValueError, match=r"auto.*pallas.*xla"):
        tier.validate_kernels_cfg()
    cfg.KERNELS.OPT_UPDATE = "auto"
    cfg.KERNELS.DECODE_BLOCK = 100
    with pytest.raises(ValueError, match=r"100 % 8 = 4"):
        tier.validate_kernels_cfg()


def test_engine_refuses_unaligned_cache_tiles():
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.lm.generate import GenerateEngine

    cfg.defrost()
    cfg.MODEL.ARCH = "gpt_nano"
    cfg.MODEL.NUM_CLASSES = 320
    cfg.LM.SEQ_LEN = 256
    cfg.KERNELS.DECODE_ATTN = "pallas"
    cfg.KERNELS.DECODE_BLOCK = 128
    model = trainer.build_model_from_cfg()
    with pytest.raises(ValueError) as e:
        GenerateEngine(
            model, {"params": {}}, max_new_tokens=8, prompt_len=8,
            batch_tiles=[1], cache_tiles=[192],
        )
    # both numbers and the remainder arithmetic must be in the message
    assert "192" in str(e.value) and "128" in str(e.value)
    assert "192 % 128 = 64" in str(e.value)


def test_select_emits_telemetry_and_fallback(tmp_path):
    from distribuuuu_tpu.telemetry import schema, spans

    path = spans.setup_telemetry(str(tmp_path), rank=0)
    try:
        cfg.defrost()
        cfg.KERNELS.OPT_UPDATE = "pallas"
        cfg.KERNELS.CONV_EPILOGUE = "pallas"  # forced ⇒ fallback is loud
        assert tier.select("opt_update", supported=True) == "pallas"
        assert tier.select("opt_update", supported=True) == "pallas"  # dedup
        assert tier.select(
            "conv_epilogue", supported=False, reason="kernel (3, 3)"
        ) == "xla"
    finally:
        spans.close_telemetry()
    recs = [json.loads(ln) for ln in open(path)]
    for r in recs:
        if r.get("kind", "").startswith("kernel."):
            schema.validate_record(r)
    sel = [r for r in recs if r.get("kind") == "kernel.select"]
    fb = [r for r in recs if r.get("kind") == "kernel.fallback"]
    assert [s["op"] for s in sel].count("opt_update") == 1  # emitted once
    assert sel[0]["impl"] == "pallas" and sel[0]["requested"] == "pallas"
    assert fb and fb[0]["op"] == "conv_epilogue"
    assert "kernel (3, 3)" in fb[0]["reason"]


def test_auto_stays_on_xla_off_tpu():
    """`auto` must never pick interpret-mode pallas on the CPU backend —
    the tier-1 suite runs the reference paths unless a test forces."""
    assert tier.select("opt_update", supported=True) == "xla"
    from distribuuuu_tpu.ops.pallas.opt_update import fused_update_for

    assert fused_update_for() is None


def test_run_report_kernels_section(tmp_path):
    import run_report

    tdir = tmp_path / "telemetry"
    os.makedirs(tdir)
    recs = [
        {"kind": "clock", "rank": 0, "t": 0.0, "unix": 0.0, "mono": 0.0},
        {"kind": "kernel.select", "rank": 0, "t": 1.0, "op": "opt_update",
         "impl": "pallas", "requested": "auto"},
        {"kind": "kernel.fallback", "rank": 0, "t": 1.0,
         "op": "conv_epilogue", "requested": "pallas",
         "reason": "kernel (3, 3) is not pointwise (1, 1)"},
        {"kind": "span", "rank": 0, "t": 1.0, "v": 1, "name": "step",
         "t0": 0.0, "dur": 0.01, "track": "pipeline", "phase": "train"},
    ]
    with open(tdir / "rank00000.jsonl", "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    rep = run_report.build_report(str(tmp_path))
    kern = rep["kernels"]
    assert kern["selected"]["opt_update"]["impl"] == "pallas"
    assert kern["fallbacks"][0]["op"] == "conv_epilogue"


def test_bench_index_kernel_series_and_resnet50_reference():
    """BENCH_r09's kernel_* series must ride the index WITHOUT touching
    the img/s regression reference (the PR 8 clobbering lesson): the
    resnet50 throughput series still sources BENCH_r05.json after
    regeneration, and run_report's gate extractor still reads it."""
    import bench_history
    import run_report

    root = os.path.join(os.path.dirname(__file__), "..")
    index = bench_history.build_index(root)
    series = index["series"]
    kernel_series = [k for k in series if k.startswith("kernel_")]
    assert kernel_series, "BENCH_r09.json kernel series missing"
    for k in kernel_series:
        assert "images_per_sec" not in k and "img_per_sec" not in k
    ref = series["resnet50_train_images_per_sec_per_chip"][-1]
    assert ref["source"] == "BENCH_r05.json"
    gates = run_report.comparable_metrics(index)
    assert gates["img_per_sec"] == pytest.approx(ref["value"])


def test_bench_r09_artifact_committed():
    """The acceptance artifact: BENCH_r09.json carries the per-kernel
    A/B matrix with the roofline movement and the recorded caveat."""
    root = os.path.join(os.path.dirname(__file__), "..")
    with open(os.path.join(root, "BENCH_r09.json")) as f:
        doc = json.load(f)
    assert "cost_analysis" in doc["caveat"] or "custom call" in doc["caveat"]
    for name in ("opt_update_sgd", "opt_update_adamw", "decode_attn",
                 "conv_epilogue"):
        row = doc["kernels"][name]
        assert row["bytes_ratio_xla_over_pallas"] > 1.0
        assert row["pallas"]["intensity"] > row["xla"]["intensity"]
    assert doc["kernels"]["opt_update_sgd"]["bit_exact"]
    assert doc["kernels"]["opt_update_adamw"]["bit_exact"]
    for label in ("efficientnet_b0_train_opt_update", "gen_decode_b4_c256"):
        row = doc["step_ab"][label]
        assert row["intensity_with_kernel"] > row["intensity_xla"]
