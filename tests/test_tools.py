"""Smoke tests for the tools/ CLIs (zoo_check, data_bench)."""

import os
import subprocess
import sys
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=400):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    return subprocess.run(
        [sys.executable] + args, env=env, capture_output=True, text=True,
        timeout=timeout, cwd=REPO,
    )


@pytest.mark.slow  # dominates the fast tier; full tier covers it
def test_zoo_check_single_arch():
    out = _run(
        ["tools/zoo_check.py", "--arch", "resnet18", "--batch", "2",
         "--im-size", "32"]
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-1000:]
    assert "1/1 archs passed" in out.stdout


@pytest.mark.slow
def test_zoo_check_reports_failure():
    out = _run(
        ["tools/zoo_check.py", "--arch", "nosuch_arch", "--batch", "2",
         "--im-size", "32"]
    )
    assert out.returncode == 1
    assert "FAIL nosuch_arch" in out.stdout
    assert "0/1 archs passed" in out.stdout


def test_data_bench_tiny_corpus():
    out = _run(
        ["tools/data_bench.py", "--n-images", "32", "--batch-size", "8",
         "--epochs", "1", "--im-size", "64", "--workers", "2"]
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-1000:]
    assert "input_pipeline_pil_images_per_sec" in out.stdout


def test_data_bench_rejects_empty_measurement():
    out = _run(["tools/data_bench.py", "--n-images", "4"])
    assert out.returncode != 0
    assert "drop_last" in out.stderr + out.stdout
