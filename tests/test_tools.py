"""Smoke tests for the tools/ CLIs (zoo_check, data_bench)."""

import os
import subprocess
import sys
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=400):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    return subprocess.run(
        [sys.executable] + args, env=env, capture_output=True, text=True,
        timeout=timeout, cwd=REPO,
    )


@pytest.mark.slow  # dominates the fast tier; full tier covers it
def test_zoo_check_single_arch():
    out = _run(
        ["tools/zoo_check.py", "--arch", "resnet18", "--batch", "8",
         "--im-size", "32"]
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-1000:]
    assert "1/1 archs passed" in out.stdout


@pytest.mark.slow
def test_zoo_check_reports_failure():
    out = _run(
        ["tools/zoo_check.py", "--arch", "nosuch_arch", "--batch", "8",
         "--im-size", "32"]
    )
    assert out.returncode == 1
    assert "FAIL nosuch_arch" in out.stdout
    assert "0/1 archs passed" in out.stdout


def test_data_bench_tiny_corpus():
    out = _run(
        ["tools/data_bench.py", "--n-images", "32", "--batch-size", "8",
         "--epochs", "1", "--im-size", "64", "--workers", "2"]
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-1000:]
    assert "input_pipeline_pil_images_per_sec" in out.stdout


def test_data_bench_rejects_empty_measurement():
    out = _run(["tools/data_bench.py", "--n-images", "4"])
    assert out.returncode != 0
    assert "drop_last" in out.stderr + out.stdout


def test_data_bench_shards_paired_mode(tmp_path):
    """--backend shards: one paired imagefolder-vs-record-shards command,
    same decode kernel, writing the comparison JSON (the SHARDS_r01.json
    artifact shape)."""
    import json

    json_out = tmp_path / "shards_bench.json"
    out = _run(
        ["tools/data_bench.py", "--backend", "shards", "--n-images", "32",
         "--batch-size", "8", "--epochs", "1", "--im-size", "64",
         "--workers", "2", "--shard-mb", "0.05", "--json-out", str(json_out)]
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-1000:]
    assert "input_pipeline_imagefolder_images_per_sec" in out.stdout
    assert "input_pipeline_shards_images_per_sec" in out.stdout
    doc = json.loads(json_out.read_text())
    assert doc["imagefolder"]["img_per_sec"] > 0
    assert doc["shards"]["img_per_sec"] > 0
    assert doc["shards_speedup"] > 0
    assert doc["corpus"]["shards"] >= 1


@pytest.mark.slow
def test_zoo_check_yaml_mode():
    """--yamls certifies shipped configs through the exact train_net merge
    path (VERDICT r5 item 8)."""
    out = _run(
        ["tools/zoo_check.py", "--yamls", "--arch", "resnet18,vit_small",
         "--batch", "8", "--im-size", "32"]
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-1000:]
    assert "resnet18 [resnet18.yaml]" in out.stdout
    assert "vit_small [vit_small.yaml]" in out.stdout
    assert "2/2 archs passed" in out.stdout


@pytest.mark.slow
def test_serve_bench_smoke(tmp_path):
    """serve_bench produces the frontier report: ≥2 offered loads, p50/p99,
    and both engine modes at each load."""
    import json

    report = tmp_path / "BENCH_serve.json"
    out = _run(
        ["tools/serve_bench.py", "--im-size", "16", "--num-classes", "10",
         "--duration", "1", "--clients", "1", "--out", str(report)],
        timeout=500,
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-1000:]
    rep = json.loads(report.read_text())
    loads = {r["offered_rps"] for r in rep["open_loop"]}
    assert len(loads) >= 2
    for r in rep["open_loop"]:
        assert r["p50_ms"] > 0 and r["p99_ms"] >= r["p50_ms"]
    modes = {r["mode"] for r in rep["open_loop"]}
    assert modes == {"dynamic", "batch1"}


@pytest.mark.slow
def test_serve_net_batch_mode(tmp_path):
    """serve_net.py one-shot batch mode: uint8 npy in, logits npy out."""
    import numpy as np

    src, dst = tmp_path / "in.npy", tmp_path / "out.npy"
    rng = np.random.default_rng(0)
    np.save(src, rng.integers(0, 256, (4, 16, 16, 3), dtype=np.uint8))
    out = _run(
        ["serve_net.py", "--cfg", "config/resnet18.yaml",
         "--batch-input", str(src), "--batch-output", str(dst),
         "MODEL.NUM_CLASSES", "10", "MODEL.BN_GROUP", "8",
         "TRAIN.IM_SIZE", "16", "TEST.IM_SIZE", "16",
         "DEVICE.COMPUTE_DTYPE", "float32", "SERVE.MAX_BATCH", "2"],
    )
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-1000:]
    logits = np.load(dst)
    assert logits.shape == (4, 10)
    assert np.isfinite(logits).all()
