"""Preemption-safe checkpointing (utils/preempt.py) — TPU-native extension.

The reference's recovery story is restart + epoch-boundary auto-resume
(ref: /root/reference/distribuuuu/trainer.py:143-149): an interrupted
epoch's optimizer progress is lost. Here SIGTERM stops the epoch loop at
the next dispatch boundary, writes a mid-epoch checkpoint, and the next
run's auto-resume prefers it — the interrupted epoch re-runs from the
preserved state.

Covered: the signal handler itself (real os.kill), the epoch-loop exit +
save + resume-preference chain end-to-end through train_model (flag
injected deterministically — no timing races), and the checkpoint
preference ordering (preempt_ep_e beats ckpt_ep_{e-1}, superseded by
ckpt_ep_e).
"""

import os
import signal

import numpy as np
import pytest

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.utils import checkpoint as ckpt, preempt


@pytest.fixture(autouse=True)
def _clean_flag():
    preempt.reset()
    yield
    preempt.reset()


def test_sigterm_sets_the_flag():
    preempt.install()
    assert not preempt.requested_local()
    os.kill(os.getpid(), signal.SIGTERM)
    assert preempt.requested_local()
    assert preempt.requested_global()  # world size 1 → local answer


def test_checkpoint_preference_ordering(tmp_path):
    cfg.OUT_DIR = str(tmp_path)
    d = ckpt.get_checkpoint_dir()
    os.makedirs(os.path.join(d, "ckpt_ep_001"))
    os.makedirs(os.path.join(d, "preempt_ep_002"))
    # mid-epoch state of interrupted epoch 2 outranks completed epoch 1
    assert ckpt.get_last_checkpoint().endswith("preempt_ep_002")
    # ...and is stale once epoch 2 completed
    os.makedirs(os.path.join(d, "ckpt_ep_002"))
    assert ckpt.get_last_checkpoint().endswith("ckpt_ep_002")
    assert ckpt.has_checkpoint()


def test_preempt_only_checkpoint_is_resumable(tmp_path):
    cfg.OUT_DIR = str(tmp_path)
    d = ckpt.get_checkpoint_dir()
    os.makedirs(os.path.join(d, "preempt_ep_000"))
    assert ckpt.has_checkpoint()
    assert ckpt.get_last_checkpoint().endswith("preempt_ep_000")


def _dummy_cfg(tmp_path):
    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.DUMMY_INPUT = True
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.TRAIN.BATCH_SIZE = 2
    cfg.TRAIN.IM_SIZE = 32
    cfg.TRAIN.PRINT_FREQ = 2
    cfg.TEST.BATCH_SIZE = 4
    cfg.TEST.IM_SIZE = 32
    cfg.OPTIM.MAX_EPOCH = 3
    cfg.OUT_DIR = str(tmp_path)
    cfg.RNG_SEED = 0


@pytest.mark.slow
def test_eval_preemption_defers_validation_to_resume(tmp_path, monkeypatch):
    """Preemption DURING validate: the completed epoch's trained state is
    saved with an eval-pending marker; the resumed run validates it first
    (so it gets best-tracking and its real checkpoint), then continues.
    The superseded preempt checkpoint is pruned."""
    from distribuuuu_tpu import trainer

    _dummy_cfg(tmp_path)
    cfg.OPTIM.MAX_EPOCH = 2

    real_validate = trainer.validate
    calls = {"n": 0}

    def fake_validate(*a, **k):
        calls["n"] += 1
        if calls["n"] == 1:
            return None  # what validate() returns when preempted mid-eval
        return real_validate(*a, **k)

    monkeypatch.setattr(trainer, "validate", fake_validate)
    trainer.train_model()
    d = ckpt.get_checkpoint_dir()
    names = set(os.listdir(d))
    assert "preempt_ep_001" in names and "ckpt_ep_000" not in names, names
    restored = ckpt.load_checkpoint(ckpt.get_last_checkpoint())
    assert int(restored["epoch"]) == 0  # epoch 0 training IS complete
    assert int(restored["pending_eval"]) == 0

    # rerun: the pending eval runs first, epoch 0 gets its real checkpoint
    # + best tracking, training continues through epoch 1, and the stale
    # preempt checkpoint is pruned
    monkeypatch.setattr(trainer, "validate", real_validate)
    best = trainer.train_model()
    names = set(os.listdir(d))
    assert {"ckpt_ep_000", "ckpt_ep_001"} <= names, names
    assert "preempt_ep_001" not in names, names
    assert np.isfinite(best) and best > 50.0


@pytest.mark.slow
def test_final_epoch_eval_preempt_terminates_cleanly(tmp_path, monkeypatch):
    """Eval-preempt on the LAST epoch: the resume validates it, writes its
    real checkpoint, and prunes the preempt checkpoint — a further restart
    must terminate immediately instead of re-validating forever."""
    from distribuuuu_tpu import trainer

    _dummy_cfg(tmp_path)
    cfg.OPTIM.MAX_EPOCH = 1  # epoch 0 is the final epoch

    real_validate = trainer.validate
    monkeypatch.setattr(trainer, "validate", lambda *a, **k: None)
    trainer.train_model()  # eval of epoch 0 "preempted"
    d = ckpt.get_checkpoint_dir()
    assert "preempt_ep_001" in os.listdir(d)

    monkeypatch.setattr(trainer, "validate", real_validate)
    trainer.train_model()  # resume: pending eval runs, real ckpt written
    names = set(os.listdir(d))
    assert "ckpt_ep_000" in names, names
    assert "preempt_ep_001" not in names, names  # pruned — nothing stale

    # third run: resumes from ckpt_ep_000, loop range empty, returns fast
    calls = {"n": 0}

    def counting_validate(*a, **k):
        calls["n"] += 1
        return real_validate(*a, **k)

    monkeypatch.setattr(trainer, "validate", counting_validate)
    trainer.train_model()
    assert calls["n"] == 0  # no re-validation churn on restart


@pytest.mark.slow
def test_preemption_saves_and_resume_continues(tmp_path, monkeypatch):
    """End-to-end through train_model: epoch 0 completes, the flag fires
    during epoch 1 → mid-epoch save + early return; the rerun resumes
    INTO epoch 1 (not from its start-of-epoch boundary) and finishes."""
    from distribuuuu_tpu import trainer

    _dummy_cfg(tmp_path)

    # deterministic preemption: trip the flag partway through epoch 1
    # (each call to requested_global == one dispatch-window check)
    calls = {"n": 0}
    epoch0_windows = 8  # dummy epoch = 8 host batches at these sizes

    def fake_requested():
        calls["n"] += 1
        return calls["n"] > epoch0_windows + 3
    monkeypatch.setattr(preempt, "requested_global", fake_requested)

    trainer.train_model()
    d = ckpt.get_checkpoint_dir()
    names = sorted(os.listdir(d))
    assert "ckpt_ep_000" in names, names           # epoch 0 completed
    assert "preempt_ep_001" in names, names        # epoch 1 interrupted
    assert "ckpt_ep_001" not in names, names

    # restored cursor points at re-running epoch 1
    restored = ckpt.load_checkpoint(ckpt.get_last_checkpoint())
    assert int(restored["epoch"]) == 0

    # rerun without preemption: resumes into epoch 1 and finishes all 3
    monkeypatch.setattr(preempt, "requested_global", lambda: False)
    best = trainer.train_model()
    names = sorted(os.listdir(d))
    assert {"ckpt_ep_000", "ckpt_ep_001", "ckpt_ep_002"} <= set(names)
    assert np.isfinite(best)
