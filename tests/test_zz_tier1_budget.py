"""Tier-1 wall-clock guard (ISSUE 16 satellite).

The driver runs tier-1 under ``timeout -k 10 870`` (ROADMAP.md); a suite
that creeps past the budget dies with SIGTERM and ZERO diagnostics about
which tests got slow. This file is named ``test_zz_*`` so it collects
LAST under ``-p no:randomly``: by the time it runs, (almost) the whole
session's cost is known, and a breach fails HERE with a readable message
instead of as an opaque timeout kill.

The guard only arms on full-suite runs (hundreds of items): targeted
runs (``pytest tests/test_fleet.py``) and slow-tier runs measure nothing
about the tier-1 budget.

When this fails: demote the heaviest tests to the slow tier
(``@pytest.mark.slow`` — run them via ``-m slow``), don't raise the
budget. ``--durations=25`` names the offenders.
"""

import os
import time

import pytest

# soft budget (s): the driver timeout is 870; failing at 780 leaves
# margin for collection + teardown variance on a loaded 1-core host
SOFT_BUDGET_S = 780.0

# below this many collected items this is a targeted run, not tier-1
FULL_SUITE_MIN_ITEMS = 300


def test_tier1_wall_clock_within_budget(request):
    if os.environ.get("DTPU_SKIP_T1_BUDGET"):
        pytest.skip("budget guard disabled via DTPU_SKIP_T1_BUDGET")
    items = len(request.session.items)
    if items < FULL_SUITE_MIN_ITEMS:
        pytest.skip(
            f"targeted run ({items} items): the budget guard only "
            f"measures full tier-1 sessions"
        )
    t0 = getattr(request.config, "_t1_start", None)
    assert t0 is not None, "conftest pytest_configure did not stamp _t1_start"
    elapsed = time.monotonic() - t0
    assert elapsed < SOFT_BUDGET_S, (
        f"tier-1 took {elapsed:.0f}s of its {SOFT_BUDGET_S:.0f}s soft "
        f"budget (driver hard-kills at 870s): demote the heaviest tests "
        f"to @pytest.mark.slow (find them with --durations=25) instead "
        f"of letting the suite die as an opaque timeout"
    )
