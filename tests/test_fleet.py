"""Serving fleet (distribuuuu_tpu/serve/fleet/): least-loaded policy from
synthetic registry snapshots, warm-up-gated routability, drain-before-exit
ordering, idempotent reroute on replica failure, verbatim backpressure
passthrough, autoscaler hysteresis math, and fleet.* telemetry schema —
all fake-driven (no real replica processes) in the fast tier, plus a
slow-tier 2-replica end-to-end acceptance run asserting served logits
equal the eval forward through the router.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.serve import protocol
from distribuuuu_tpu.serve.fleet import (
    AutoscalePolicy,
    Autoscaler,
    FleetService,
    LoadSnapshot,
    Observation,
    PoolManager,
    Router,
    load_score,
    pick_replica,
    warmed_up,
)
from distribuuuu_tpu.telemetry import schema

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- least-loaded policy (pure, synthetic snapshots) -------------------------

def test_load_score_orders_by_queued_work():
    idle = LoadSnapshot(inflight=0, queue_depth=0, occupancy=0.0, ewma_ms=5.0)
    queued = LoadSnapshot(inflight=2, queue_depth=4, occupancy=0.0, ewma_ms=5.0)
    slow = LoadSnapshot(inflight=0, queue_depth=0, occupancy=0.0, ewma_ms=50.0)
    full = LoadSnapshot(inflight=0, queue_depth=0, occupancy=1.0, ewma_ms=5.0)
    assert load_score(idle) < load_score(queued)
    assert load_score(idle) < load_score(slow)
    assert load_score(idle) < load_score(full)  # occupancy weighs in


def test_pick_replica_least_loaded_and_skips_unroutable():
    snaps = [
        LoadSnapshot(inflight=3, queue_depth=2, occupancy=0.9, ewma_ms=10.0),
        LoadSnapshot(inflight=0, queue_depth=0, occupancy=0.1, ewma_ms=10.0),
        None,  # unroutable (draining/dead/warming)
    ]
    assert pick_replica(snaps) == 1
    assert pick_replica([None, None, None]) is None
    assert pick_replica([]) is None


def test_pick_replica_round_robins_ties():
    # equally idle replicas share cold traffic via the rr tiebreak
    snaps = [LoadSnapshot(), LoadSnapshot(), LoadSnapshot()]
    picks = {pick_replica(snaps, rr=r) for r in range(3)}
    assert picks == {0, 1, 2}


def test_router_pick_from_registry_snapshots():
    """The router's pick over replica records whose queue depth/occupancy
    came from (synthetic) replica Registry stats snapshots."""
    router = Router()
    a = router.add_replica("127.0.0.1", 1001)
    b = router.add_replica("127.0.0.1", 1002)
    router.mark_routable(a.id)
    router.mark_routable(b.id)
    # a is deep in queued work per its last stats probe; b is idle
    a.stats = {"queue_depth": 12, "batch_occupancy": 1.0}
    b.stats = {"queue_depth": 0, "batch_occupancy": 0.2}
    a.ewma_ms = b.ewma_ms = 8.0
    for _ in range(4):
        assert router._pick(set()).id == b.id
    # draining stops routing even to the least-loaded replica
    router.mark_draining(b.id)
    assert router._pick(set()).id == a.id
    # excluded (already tried) + draining leaves nothing
    assert router._pick({a.id}) is None


# -- fakes for the lifecycle tests -------------------------------------------

class FakeHandle:
    """A fake replica process: records lifecycle calls, 'exits' when
    terminated or killed."""

    def __init__(self, events: list, rid: int):
        self.events = events
        self.rid = rid
        self.pid = 4000 + rid
        self._rc = None

    def poll(self):
        return self._rc

    def terminate(self):
        self.events.append(("terminate", self.rid))
        self._rc = 0

    def kill(self):
        self.events.append(("kill", self.rid))
        self._rc = -9

    def wait(self, timeout=None):
        return self._rc


def make_fake_pool(events, probe, **kw):
    router = Router()
    orig_mark_draining = router.mark_draining

    def mark_draining(rid):
        events.append(("mark_draining", rid))
        orig_mark_draining(rid)

    router.mark_draining = mark_draining
    pool = PoolManager(
        router,
        lambda rid, port: FakeHandle(events, rid),
        probe=probe,
        warmup_timeout_s=kw.pop("warmup_timeout_s", 2.0),
        warmup_poll_s=0.005,
        health_period_s=0.05,
        **kw,
    )
    return router, pool


WARM_STATS = {
    "buckets": [1, 2, 4], "n_compiles": 3, "queue_depth": 0,
    "batch_occupancy": 0.0, "jit_compiles": 3, "aot_compiles": 3,
}


def test_warmup_gates_routability():
    """A replica must NOT be routable until its probe reports every bucket
    shape AOT-compiled."""
    events, responses = [], []

    def probe(addr):
        if not responses:
            raise ConnectionRefusedError("not listening yet")
        return responses[0]

    router, pool = make_fake_pool(events, probe)
    pool.set_target(1)
    done = threading.Thread(target=pool.add_replica, daemon=True)
    done.start()
    time.sleep(0.05)
    assert router.n_routable() == 0  # not even listening
    responses.append({"buckets": [1, 2, 4], "n_compiles": 1})  # mid-compile
    time.sleep(0.05)
    assert router.n_routable() == 0  # up but NOT warm -> still not routable
    responses[0] = dict(WARM_STATS)
    done.join(timeout=2)
    assert not done.is_alive()
    assert router.n_routable() == 1
    rep = router.replicas()[0]
    assert rep.stats["jit_compiles"] == 3  # warm baseline recorded
    assert warmed_up(rep.stats)


def test_warmup_timeout_removes_replica():
    events = []
    router, pool = make_fake_pool(
        events, lambda addr: {"buckets": [1, 2], "n_compiles": 1},
        warmup_timeout_s=0.05,
    )
    pool.add_replica(wait=True)
    assert router.replicas() == []
    assert ("kill", 0) in events  # the stuck process was put down


def test_drain_stop_marks_draining_before_sigterm():
    """The drain-before-exit ordering: the router stops routing to the
    replica BEFORE the process gets SIGTERM, and the replica leaves the
    router only after it exits."""
    events = []
    router, pool = make_fake_pool(events, lambda addr: dict(WARM_STATS))
    rep = pool.add_replica(wait=True)
    assert router.n_routable() == 1
    assert pool.drain_stop(rep.id, wait=True)
    assert router.get_replica(rep.id) is None  # removed after exit
    lifecycle = [e for e in events if e[0] in ("mark_draining", "terminate")]
    assert lifecycle == [("mark_draining", rep.id), ("terminate", rep.id)]


def test_dead_replica_is_replaced_to_target():
    events = []
    router, pool = make_fake_pool(events, lambda addr: dict(WARM_STATS))
    pool.set_target(2)
    r0 = pool.add_replica(wait=True)
    pool.add_replica(wait=True)
    assert router.n_routable() == 2
    r0.proc._rc = -9  # SIGKILLed out-of-band
    pool.health_check()
    assert router.get_replica(r0.id) is None
    pool._maintain_target()  # the supervisor's replacement pass
    time.sleep(0.3)  # background warm-up of the replacement
    assert router.n_routable() == 2
    assert {r.id for r in router.replicas()} == {1, 2}  # fresh id spawned


def test_health_probe_failures_mark_dead_after_n():
    events, fail = [], {"on": False}

    def probe(addr):
        if fail["on"]:
            raise ConnectionRefusedError("down")
        return dict(WARM_STATS)

    router, pool = make_fake_pool(events, probe, health_fails=3)
    rep = pool.add_replica(wait=True)
    fail["on"] = True
    pool.health_check()
    pool.health_check()
    assert router.get_replica(rep.id) is not None  # 2 < HEALTH_FAILS
    pool.health_check()
    assert router.get_replica(rep.id) is None


# -- router dispatch over fake socket replicas -------------------------------

class FakeReplicaServer:
    """A real localhost socket speaking the serve framing, with a
    scripted responder (return bytes, or None to close the connection —
    the crashed-replica shape)."""

    def __init__(self, responder):
        self.responder = responder
        self.listener = protocol.open_listener("127.0.0.1", 0)
        self.port = self.listener.getsockname()[1]
        self.requests = 0
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._accept, daemon=True)
        self._t.start()

    def _accept(self):
        self.listener.settimeout(0.05)
        while not self._stop.is_set():
            try:
                conn, _ = self.listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn):
        with conn:
            while True:
                try:
                    payload = protocol.recv_frame(conn)
                except (OSError, ValueError):
                    return
                if payload is None:
                    return
                self.requests += 1
                resp = self.responder(payload)
                if resp is None:
                    return  # slam the connection shut mid-request
                try:
                    protocol.send_frame(conn, resp)
                except OSError:
                    return

    def close(self):
        self._stop.set()
        self.listener.close()


def _router_over(servers) -> Router:
    router = Router(request_timeout_s=5.0)
    for srv in servers:
        rep = router.add_replica("127.0.0.1", srv.port)
        router.mark_routable(rep.id)
    return router


def test_backpressure_passthrough_verbatim():
    """When every replica rejects with queue_full, the client receives a
    replica's retry-after rejection VERBATIM — the router must not queue
    the request itself."""
    rejection = json.dumps(
        {"error": "queue_full", "retry_after_ms": 123.4}
    ).encode()
    servers = [FakeReplicaServer(lambda p: rejection) for _ in range(2)]
    try:
        router = _router_over(servers)
        t0 = time.perf_counter()
        resp = router.dispatch(b"fake-image-payload")
        elapsed = time.perf_counter() - t0
        assert resp == rejection  # byte-for-byte the admission.py shape
        assert elapsed < 1.0  # rejected immediately, never queued/waited
        # every replica was offered the request before giving up
        assert all(srv.requests == 1 for srv in servers)
        snap = router.stats()
        assert snap["rejected"] == 1 and snap["requests"] == 0
    finally:
        for srv in servers:
            srv.close()


def test_reroute_on_replica_failure_is_idempotent():
    """A replica dying mid-request reroutes the SAME payload to the next
    replica; the client sees one success, the router records the reroute
    and stops routing to the dead replica."""
    seen = []
    ok = json.dumps({"pred": 7, "topk": [7], "logits": [0.0]}).encode()

    def good(payload):
        seen.append(payload)
        return ok

    dead = FakeReplicaServer(lambda p: None)  # closes on every request
    alive = FakeReplicaServer(good)
    try:
        router = _router_over([dead, alive])
        dead_rep, alive_rep = router.replicas()
        # bias the pick toward the dead replica so the reroute must happen
        alive_rep.stats = {"queue_depth": 5, "batch_occupancy": 1.0}
        alive_rep.ewma_ms = dead_rep.ewma_ms = 10.0
        payload = b"idempotent-request"
        resp = router.dispatch(payload)
        assert resp == ok
        assert seen == [payload]  # the same bytes arrived once, rerouted
        snap = router.stats()
        assert snap["rerouted"] == 1 and snap["replica_failures"] == 1
        assert snap["requests"] == 1
        assert not router.get_replica(dead_rep.id).routable
    finally:
        dead.close()
        alive.close()


def test_all_dead_returns_no_routable_error():
    dead = FakeReplicaServer(lambda p: None)
    try:
        router = _router_over([dead])
        resp = json.loads(router.dispatch(b"x"))
        assert resp["error"] == "no_routable_replicas"
        assert resp["retry_after_ms"] > 0
    finally:
        dead.close()


def test_router_serve_forwards_and_answers_stats():
    """End-to-end through the router's own accept loop: a data frame is
    forwarded to a replica, a stats control frame is answered by the
    router itself."""
    ok = json.dumps({"pred": 3, "topk": [3], "logits": [1.0]}).encode()
    srv = FakeReplicaServer(lambda p: ok)
    router = _router_over([srv])
    listener = protocol.open_listener("127.0.0.1", 0)
    port = listener.getsockname()[1]
    stop = threading.Event()
    t = threading.Thread(
        target=router.serve, args=(listener, stop.is_set),
        kwargs=dict(poll_s=0.05), daemon=True,
    )
    t.start()
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=5) as conn:
            protocol.send_frame(conn, b"an-image")
            assert protocol.recv_frame(conn) == ok
            protocol.send_frame(conn, protocol.ctrl_request("stats"))
            stats = json.loads(protocol.recv_frame(conn))
        assert stats["replicas"] == 1 and stats["requests"] == 1
        assert stats["per_replica"][0]["requests"] == 1
    finally:
        stop.set()
        t.join(timeout=5)
        srv.close()


# -- autoscaler hysteresis math (pure) ---------------------------------------

def _policy(**kw):
    defaults = dict(
        p99_target_ms=100.0, queue_high=10, queue_low=1,
        scale_down_frac=0.5, breach_n=3, cooldown_s=10.0,
        min_replicas=1, max_replicas=4,
    )
    defaults.update(kw)
    return AutoscalePolicy(**defaults)


def _hot(n=1):
    return Observation(p99_ms=500.0, queue_depth=0, n_replicas=n)


def _calm(n=2):
    return Observation(p99_ms=10.0, queue_depth=0, n_replicas=n)


def _mid(n=2):
    return Observation(p99_ms=80.0, queue_depth=0, n_replicas=n)


def test_autoscale_needs_consecutive_breaches():
    p = _policy()
    assert p.decide(0.0, _hot()) == 0
    assert p.decide(1.0, _hot()) == 0
    assert p.decide(2.0, _hot()) == +1  # third consecutive breach
    # queue watermark alone also breaches
    p = _policy()
    q = Observation(p99_ms=10.0, queue_depth=50, n_replicas=1)
    assert [p.decide(float(t), q) for t in range(3)] == [0, 0, +1]


def test_autoscale_streak_resets_on_calm_window():
    p = _policy()
    p.decide(0.0, _hot())
    p.decide(1.0, _hot())
    p.decide(2.0, _mid(1))  # neither hot nor calm: both streaks reset
    assert p.decide(3.0, _hot()) == 0
    assert p.decide(4.0, _hot()) == 0
    assert p.decide(5.0, _hot()) == +1


def test_autoscale_cooldown_blocks_consecutive_actions():
    p = _policy(breach_n=1, cooldown_s=10.0)
    assert p.decide(0.0, _hot(1)) == +1
    assert p.decide(1.0, _hot(2)) == 0  # evidence real but inside cooldown
    assert p.decide(9.9, _hot(2)) == 0
    assert p.decide(11.0, _hot(2)) == +1  # cooldown expired


def test_autoscale_scale_down_and_clamps():
    p = _policy(breach_n=2, cooldown_s=0.1)
    assert p.decide(0.0, _calm(3)) == 0
    assert p.decide(1.0, _calm(3)) == -1
    # at the min budget, calm windows never go below
    p = _policy(breach_n=1, cooldown_s=0.0)
    assert p.decide(0.0, _calm(1)) == 0
    # at the max budget, hot windows never go above
    assert p.decide(1.0, _hot(4)) == 0


def test_autoscale_down_requires_both_calm_signals():
    p = _policy(breach_n=1, cooldown_s=0.0)
    # p99 calm but queue above the low watermark -> hold
    assert p.decide(0.0, Observation(p99_ms=10.0, queue_depth=5,
                                     n_replicas=2)) == 0
    # p99 at 0.6x target (not under scale_down_frac=0.5) -> hold
    assert p.decide(1.0, Observation(p99_ms=60.0, queue_depth=0,
                                     n_replicas=2)) == 0


def test_autoscale_validation():
    with pytest.raises(ValueError, match="SCALE_DOWN_FRAC"):
        _policy(scale_down_frac=1.5)
    with pytest.raises(ValueError, match="MIN_REPLICAS"):
        _policy(min_replicas=5, max_replicas=2)


def test_autoscaler_step_acts_through_pool():
    """The loop wiring: a hot router window scales the pool target up."""

    class FakePool:
        target_size = 1

        def scale_to(self, n, wait=True):
            self.target_size = n
            return n

    router = Router()
    now = time.perf_counter()
    with router._lock:
        router._recent = [(now, 0.5, None)] * 50  # 500 ms, fresh, untraced
    pool = FakePool()
    scaler = Autoscaler(
        router, pool,
        _policy(breach_n=2, cooldown_s=0.0), eval_period_s=5.0,
    )
    assert scaler.step(0.0) == 0
    assert scaler.step(1.0) == +1
    assert pool.target_size == 2


# -- fleet.* telemetry schema -------------------------------------------------

def test_fleet_kinds_declared_and_records_validate(tmp_path):
    """The fleet.* record kinds are declared in telemetry/schema.py and
    every record the router/pool/autoscaler emit validates against them
    (the dynamic half of tools/check_telemetry_schema.py's static gate)."""
    from distribuuuu_tpu.telemetry import close_telemetry, setup_telemetry

    for kind in ("fleet.stats", "fleet.replica", "fleet.scale"):
        assert kind in schema.KINDS
    router = Router()
    rep = router.add_replica("127.0.0.1", 1001)
    router.mark_routable(rep.id)
    path = setup_telemetry(str(tmp_path), rank=0)
    try:
        router.emit_telemetry()
        from distribuuuu_tpu.telemetry import spans

        spans.emit_event(
            "fleet.scale", action="scale_up", reason="test",
            n_before=1, n_after=2,
        )
    finally:
        close_telemetry()
    kinds_seen = set()
    with open(path) as f:
        for line in f:
            rec = json.loads(line)
            schema.validate_record(rec)  # raises on undeclared/missing
            kinds_seen.add(rec["kind"])
    assert {"fleet.stats", "fleet.replica", "fleet.scale"} <= kinds_seen


def test_telemetry_schema_static_check_covers_fleet():
    """tools/check_telemetry_schema.py scans the fleet emit sites clean
    and sees the fleet.* kinds."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import check_telemetry_schema as chk
    finally:
        sys.path.pop(0)
    violations, seen = chk.check_tree(os.path.join(ROOT, "distribuuuu_tpu"))
    assert violations == []
    assert {"fleet.stats", "fleet.replica", "fleet.scale"} <= seen


# -- slow tier: the real thing ------------------------------------------------

@pytest.mark.slow
def test_fleet_two_replica_e2e(tmp_path):
    """2 real replica processes behind the router: served logits through
    the fleet are numerically identical to the eval forward, traffic
    reaches the fleet with zero steady-state recompiles, and a draining
    restart under the same fleet loses nothing."""
    import jax

    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.data.transforms import normalize_in_graph
    from distribuuuu_tpu.parallel import mesh as mesh_lib
    from distribuuuu_tpu.serve.fleet.pool import probe_stats

    IM, NC = 16, 10
    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = NC
    cfg.MODEL.BN_GROUP = 8
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.DEVICE.PLATFORM = "cpu"
    cfg.TRAIN.IM_SIZE = IM
    cfg.TEST.IM_SIZE = IM
    cfg.RNG_SEED = 0
    cfg.OUT_DIR = str(tmp_path)
    cfg.SERVE.MAX_BATCH = 4
    cfg.SERVE.MAX_WAIT_MS = 2.0
    cfg.SERVE.FLEET.AUTOSCALE = False
    cfg.SERVE.FLEET.MAX_REPLICAS = 3
    cfg.SERVE.FLEET.HEALTH_PERIOD_S = 0.5
    cfg_path = os.path.join(str(tmp_path), "fleet_cfg.yaml")
    with open(cfg_path, "w") as f:
        f.write(cfg.dump())

    svc = FleetService(cfg, 2, cfg_path=cfg_path, out_dir=str(tmp_path))
    try:
        svc.start(wait=True)
        assert svc.router.n_routable() == 2, (
            "replicas failed warm-up; see fleet/replica*.log under "
            f"{tmp_path}"
        )
        baselines = {
            r.id: probe_stats(r.addr)["jit_compiles"]
            for r in svc.router.replicas()
        }

        # the same deterministic init the replicas built (same cfg/seed)
        mesh = mesh_lib.build_mesh(data=1, model=1, seq=1, pipe=1,
                                   devices=[jax.devices()[0]])
        model = trainer.build_model_from_cfg()
        state = trainer.create_train_state(
            model, jax.random.key(0), mesh, IM
        )
        variables = {"params": state.params, "batch_stats": state.batch_stats}
        fwd = jax.jit(
            lambda v, x: model.apply(v, normalize_in_graph(x), train=False)
        )

        listener = protocol.open_listener("127.0.0.1", 0)
        port = listener.getsockname()[1]
        stop = threading.Event()
        server = threading.Thread(
            target=svc.serve, args=(listener, stop.is_set),
            kwargs=dict(poll_s=0.05), daemon=True,
        )
        server.start()
        rng = np.random.default_rng(11)

        def ask(conn, img):
            import io

            buf = io.BytesIO()
            np.save(buf, img)
            protocol.send_frame(conn, buf.getvalue())
            return json.loads(protocol.recv_frame(conn))

        try:
            with socket.create_connection(
                ("127.0.0.1", port), timeout=60
            ) as conn:
                for _ in range(6):
                    img = rng.integers(0, 256, (IM, IM, 3), dtype=np.uint8)
                    resp = ask(conn, img)
                    assert "error" not in resp, resp
                    ref = np.asarray(fwd(variables, img[None]))[0]
                    np.testing.assert_allclose(
                        resp["logits"], ref, rtol=1e-5, atol=1e-5
                    )
                    assert resp["pred"] == int(np.argmax(ref))

                # draining restart under the live fleet: zero failures
                victim = svc.router.replicas()[0].id
                svc.pool.restart_replica(victim, wait=True)
                deadline = time.time() + 120
                while svc.router.n_routable() < 2 and time.time() < deadline:
                    time.sleep(0.2)
                assert svc.router.n_routable() == 2
                img = rng.integers(0, 256, (IM, IM, 3), dtype=np.uint8)
                resp = ask(conn, img)
                assert "error" not in resp, resp
                ref = np.asarray(fwd(variables, img[None]))[0]
                np.testing.assert_allclose(
                    resp["logits"], ref, rtol=1e-5, atol=1e-5
                )
        finally:
            stop.set()
            server.join(timeout=10)

        # zero steady-state recompiles fleet-wide: any replica that served
        # through the whole run still reports its warm-up jit.compiles
        for r in svc.router.replicas():
            if r.id in baselines:
                assert probe_stats(r.addr)["jit_compiles"] == baselines[r.id]
        snap = svc.router.stats()
        assert snap["requests"] == 7 and snap["rejected"] == 0
    finally:
        svc.shutdown()
