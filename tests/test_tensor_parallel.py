"""Tensor parallelism: param layout and dp×tp training on the fake mesh.

The reference is DDP-only (SURVEY.md §2.3); TP here is declarative via
``nn.with_partitioning`` metadata on kernels + GSPMD. These tests pin the
contract: annotated kernels land sharded over ``model``, training steps
produce the same numbers as pure data parallelism, and the Megatron-style
column/row pair keeps the intermediate activation sharded.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import distribuuuu_tpu.config as config
from distribuuuu_tpu import trainer
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.parallel import mesh as mesh_lib, sharding as sharding_lib, tp
from distribuuuu_tpu.utils.optim import construct_optimizer

import pytest

pytestmark = pytest.mark.slow  # multi-minute on the 1-core CPU mesh


def _make_batch(n, im=32, classes=10, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "image": rng.standard_normal((n, im, im, 3)).astype(np.float32),
        "label": rng.integers(0, classes, (n,)).astype(np.int32),
        "mask": np.ones((n,), np.float32),
    }


def _setup(data, model_axis):
    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    mesh = mesh_lib.build_mesh(data=data, model=model_axis, seq=1)
    model = trainer.build_model_from_cfg()
    state = trainer.create_train_state(model, jax.random.key(0), mesh, 32)
    return mesh, model, state


def test_params_sharded_over_model_axis():
    import jax.tree_util as jtu

    mesh, model, state = _setup(data=4, model_axis=2)
    # every conv kernel must be split on output channels over `model`;
    # every BN scale/bias stays replicated
    kernels = bns = 0
    for path, leaf in jtu.tree_flatten_with_path(state.params)[0]:
        name = jtu.keystr(path)
        if name.endswith("['kernel']") and "Conv" in name:
            assert leaf.sharding.spec == P(None, None, None, "model"), name
            kernels += 1
        if name.endswith("['scale']"):
            assert leaf.sharding.spec in (P(), P(None)), name
            bns += 1
    assert kernels > 10 and bns > 10

    # momentum buffers inherit the kernel layout (GSPMD propagation)
    tp_traces = [
        leaf
        for path, leaf in jtu.tree_flatten_with_path(state.opt_state)[0]
        if "trace" in jtu.keystr(path)
        and jtu.keystr(path).endswith("['kernel']")
        and "Conv" in jtu.keystr(path)
    ]
    assert tp_traces, "no momentum buffers found"
    for leaf in tp_traces:
        assert leaf.sharding.spec == P(None, None, None, "model")


def test_tp_matches_dp_numerics():
    batch = _make_batch(8)

    results = []
    for data, model_axis in ((8, 1), (4, 2)):
        mesh, model, state = _setup(data, model_axis)
        optimizer = construct_optimizer()
        step = trainer.make_train_step(model, optimizer, topk=5)
        gbatch = sharding_lib.shard_batch(mesh, batch)
        for _ in range(2):
            state, metrics = step(state, gbatch)
        results.append(float(metrics["loss"]))
    np.testing.assert_allclose(results[0], results[1], rtol=1e-5)


def test_column_row_parallel_pair():
    mesh = mesh_lib.build_mesh(data=4, model=2, seq=1)

    import flax.linen as nn

    class TwoLayer(nn.Module):
        @nn.compact
        def __call__(self, x):
            h = tp.ColumnParallelDense(32, dtype=jnp.float32)(x)
            h = nn.relu(h)
            return tp.RowParallelDense(8, dtype=jnp.float32)(h)

    m = TwoLayer()
    x = jnp.ones((4, 16), jnp.float32)
    variables = m.init(jax.random.key(0), x)
    shardings = tp.param_shardings(mesh, jax.eval_shape(m.init, jax.random.key(0), x))
    unboxed = nn.meta.unbox(variables)
    placed = jax.device_put(unboxed, shardings)
    col = placed["params"]["ColumnParallelDense_0"]["Dense_0"]["kernel"].sharding.spec
    row = placed["params"]["RowParallelDense_0"]["Dense_0"]["kernel"].sharding.spec
    assert col == P(None, "model"), col
    assert row == P("model", None), row
    out = jax.jit(m.apply)(placed, x)
    want = m.apply(unboxed, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=1e-5)
