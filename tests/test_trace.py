"""Request-scoped distributed tracing (ISSUE 20): deterministic
head-based sampling, the trace envelope and ctrl-frame carriage, span
propagation client edge → router → replica engine (including a reroute
hop), the traced ≡ untraced bit-identity pin, and the committed
TRACE_r01.json artifact."""

import glob
import json
import os
import socket
import sys
import threading

import jax
import numpy as np
import pytest

from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.telemetry import schema, tracectx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tools():
    tools = os.path.join(REPO, "tools")
    if tools not in sys.path:
        sys.path.insert(0, tools)
    return tools


@pytest.fixture()
def f32(monkeypatch):
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    yield


# ------------------------------------------------------------- the context


def test_sampling_is_deterministic_and_proportional():
    """The head-based decision is a pure function of the trace id (every
    edge that sees the same id agrees) and hits the requested rate over
    many ids; 0 disables, 1 keeps everything."""
    ids = [f"{i:016x}" for i in range(2000)]
    assert not any(tracectx.should_sample(t, 0.0) for t in ids)
    assert all(tracectx.should_sample(t, 1.0) for t in ids)
    kept = [t for t in ids if tracectx.should_sample(t, 0.5)]
    assert kept == [t for t in ids if tracectx.should_sample(t, 0.5)]
    assert 0.40 < len(kept) / len(ids) < 0.60
    assert tracectx.open_trace(0.0) is None  # rate 0: nothing opens


def test_envelope_roundtrip_torn_and_model_passthrough():
    from distribuuuu_tpu.serve import protocol

    ctx = tracectx.TraceContext("aa" * 8, "span-1", 123.5)
    wire = tracectx.wrap_payload(ctx, b"payload-bytes")
    back, inner = tracectx.split_payload(wire)
    assert inner == b"payload-bytes"
    assert (back.trace_id, back.parent_span, back.origin) == \
        (ctx.trace_id, ctx.parent_span, ctx.origin)
    # untraced passthrough is byte-identical in both directions
    assert tracectx.wrap_payload(None, b"x") == b"x"
    assert tracectx.split_payload(b"x") == (None, b"x")
    # the model envelope's magic is NOT a trace envelope
    menv = protocol.model_envelope("m", b"img")
    assert tracectx.split_payload(menv) == (None, menv)
    # torn envelopes refuse loudly instead of feeding garbage onward
    for torn in (wire[:10], wire[:12], tracectx.TRACE_MAGIC + b"\xff\xff"):
        with pytest.raises(ValueError, match="torn trace envelope"):
            tracectx.split_payload(torn)


def test_from_fields_tolerates_garbled_peers():
    assert tracectx.from_fields(None) is None
    assert tracectx.from_fields("nope") is None
    assert tracectx.from_fields({}) is None
    assert tracectx.from_fields({"id": 3}) is None
    ctx = tracectx.from_fields(
        {"id": "ab" * 8, "parent": 7, "origin": "bad"}
    )
    assert ctx is not None
    assert ctx.parent_span == "" and ctx.origin == 0.0


def test_trace_kinds_declared_and_span_record_validates(tmp_path):
    from distribuuuu_tpu.telemetry import close_telemetry, setup_telemetry

    assert "trace.span" in schema.KINDS
    assert "trace.exemplar" in schema.KINDS
    ctx = tracectx.TraceContext(tracectx.new_trace_id())
    setup_telemetry(str(tmp_path / "telemetry"), rank=0)
    try:
        sid = tracectx.emit_trace_span(ctx, "unit", 1.0, 0.5, slot=3)
        assert sid
        assert tracectx.emit_trace_span(None, "unit", 1.0, 0.5) == ""
    finally:
        close_telemetry()
    recs = [
        json.loads(line)
        for p in glob.glob(str(tmp_path / "telemetry" / "rank*.jsonl"))
        for line in open(p)
    ]
    spans_ = [r for r in recs if r.get("kind") == "trace.span"]
    assert len(spans_) == 1 and spans_[0]["span"] == sid
    assert spans_[0]["slot"] == 3
    for r in recs:
        schema.validate_record(r)


# ------------------------------------------- propagation over real sockets


def _tiny_engine():
    import jax.numpy as jnp

    from distribuuuu_tpu.lm.generate import GenerateEngine
    from distribuuuu_tpu.models.gpt import GPT

    model = GPT(vocab_size=320, seq_len=32, dim=32, depth=2, num_heads=2,
                dtype=jnp.float32)
    params = model.init(
        jax.random.key(0), model.dummy_input(), train=False
    )["params"]
    return GenerateEngine(
        model, {"params": params}, prompt_len=8, max_new_tokens=6,
        batch_tiles=[2], cache_tiles=[16],
    )


def test_traced_fleet_stream_builds_connected_tree(f32, tmp_path):
    """One traced generate stream over a 2-port fleet behind the real
    router (framed sockets end to end) with a dead replica forced into
    the pick order: the per-rank sink ends up holding ONE connected span
    tree containing the client edge, the router's pick/reroute/dispatch
    hops, and the engine's queue/prefill/decode spans — and the traced
    stream's tokens equal the untraced control's (the bit-identity
    pin)."""
    from distribuuuu_tpu.lm import service as lm_service
    from distribuuuu_tpu.serve import protocol
    from distribuuuu_tpu.serve.fleet.router import Router
    from distribuuuu_tpu.telemetry import close_telemetry, setup_telemetry

    eng = _tiny_engine().start()
    listeners = [protocol.open_listener("127.0.0.1", 0) for _ in range(2)]
    stop = threading.Event()
    for ln in listeners:
        threading.Thread(
            target=protocol.serve_forever, args=(eng, ln, stop.is_set),
            daemon=True,
        ).start()
    # a dead replica: a closed listener's port refuses connections
    dead = protocol.open_listener("127.0.0.1", 0)
    dead_port = dead.getsockname()[1]
    dead.close()

    router = Router(request_timeout_s=30.0)
    dead_rep = router.add_replica("127.0.0.1", dead_port)
    router.mark_routable(dead_rep.id)
    live_ids = []
    for ln in listeners:
        rep = router.add_replica("127.0.0.1", ln.getsockname()[1])
        router.mark_routable(rep.id)
        live_ids.append(rep.id)
    with router._lock:
        # bias the pick order: the dead replica looks least loaded, so
        # the traced stream MUST take a reroute hop before landing
        for rep_id in live_ids:
            router._replicas[rep_id].inflight += 4

    client_listener = protocol.open_listener("127.0.0.1", 0)
    client_port = client_listener.getsockname()[1]
    threading.Thread(
        target=router.serve, args=(client_listener, stop.is_set),
        daemon=True,
    ).start()

    prompt = [5, 7, 11]
    setup_telemetry(str(tmp_path / "telemetry"), rank=0)
    try:
        ctx = tracectx.TraceContext(tracectx.new_trace_id())
        frames = list(lm_service.generate_request(
            "127.0.0.1", client_port, tokens=prompt, max_new_tokens=4,
            trace=ctx,
        ))
        done = frames[-1]
        assert done["stream"] == "done" and "error" not in done
        # identity unification (satellite 1): every stream frame echoes
        # the trace id — the engine request and the wire share one name
        assert done["trace_id"] == ctx.trace_id
        assert all(f["trace_id"] == ctx.trace_id for f in frames)
        # untraced control: same prompt, byte-identical greedy tokens
        control = list(lm_service.generate_request(
            "127.0.0.1", client_port, tokens=prompt, max_new_tokens=4,
        ))
        assert control[-1]["tokens"] == done["tokens"]
        assert "trace_id" not in control[-1]
    finally:
        stop.set()
        close_telemetry()
        eng.drain()
        client_listener.close()

    _tools()
    import trace_request

    traces = trace_request.collect_traces(str(tmp_path))
    assert set(traces) == {ctx.trace_id}  # the control left no spans
    spans_ = traces[ctx.trace_id]
    names = {s["name"] for s in spans_}
    assert {"client.request", "router.pick", "router.reroute",
            "router.dispatch", "engine.request", "queue_wait",
            "prefill", "decode_step"} <= names
    assert trace_request.is_connected(spans_)
    # exactly one reroute hop (the dead replica), parented on dispatch
    reroutes = [s for s in spans_ if s["name"] == "router.reroute"]
    dispatch = next(s for s in spans_ if s["name"] == "router.dispatch")
    assert len(reroutes) == 1
    assert reroutes[0]["parent"] == dispatch["span"]
    assert reroutes[0]["replica"] == dead_rep.id
    # the engine hop hangs under the router hop, the router hop under
    # the client edge — a connected tree across all three layers
    engine_span = next(s for s in spans_ if s["name"] == "engine.request")
    client_span = next(s for s in spans_ if s["name"] == "client.request")
    assert engine_span["parent"] == dispatch["span"]
    assert dispatch["parent"] == client_span["span"]
    assert client_span["parent"] == ""
    sh = trace_request.stage_shares(spans_)
    assert sh["total_source"] == "router.dispatch"
    assert sh["shares"] and sh["stage_sum_ms"] > 0
    # the waterfall renders without error and names every stage
    text = trace_request.render_waterfall(ctx.trace_id, spans_)
    assert "client.request" in text and "stage shares" in text
    # exemplar plumbing: the router's ring kept the trace id and the
    # windowed stats name it
    win = router.window_stats(60.0)
    assert [e["trace"] for e in win["exemplars"]] == [ctx.trace_id]
    assert win["exemplars"][0]["latency_ms"] > 0


def test_untraced_frames_forward_byte_identically(f32):
    """The trajectory-neutrality pin at the wire level: with tracing off
    nothing re-encodes — the router forwards the EXACT ctrl bytes it
    received, and a traced client against an old (trace-ignorant)
    replica still streams fine (missing-context fallback)."""
    from distribuuuu_tpu.lm import service as lm_service
    from distribuuuu_tpu.serve import protocol
    from distribuuuu_tpu.serve.fleet.router import Router

    seen: list[bytes] = []
    rep_listener = protocol.open_listener("127.0.0.1", 0)

    def fake_replica():
        # a pre-tracing replica: ignores unknown ctrl keys, never echoes
        for _ in range(2):
            conn, _ = rep_listener.accept()
            with conn:
                payload = protocol.recv_frame(conn)
                seen.append(payload)
                protocol.send_frame(conn, json.dumps(
                    {"stream": "token", "token": 9, "i": 0}
                ).encode())
                protocol.send_frame(conn, json.dumps({
                    "stream": "done", "tokens": [9], "n": 1,
                    "reason": "max_new_tokens",
                }).encode())

    threading.Thread(target=fake_replica, daemon=True).start()
    router = Router(request_timeout_s=10.0)
    rep = router.add_replica("127.0.0.1", rep_listener.getsockname()[1])
    router.mark_routable(rep.id)
    client_listener = protocol.open_listener("127.0.0.1", 0)
    port = client_listener.getsockname()[1]
    stop = threading.Event()
    threading.Thread(
        target=router.serve, args=(client_listener, stop.is_set),
        daemon=True,
    ).start()
    try:
        # untraced: the replica receives the client's bytes verbatim
        sent = protocol.ctrl_request("generate", tokens=[1],
                                     max_new_tokens=1)
        with socket.create_connection(("127.0.0.1", port), 10) as c:
            protocol.send_frame(c, sent)
            while True:
                frame = protocol.recv_frame(c)
                if b'"stream": "done"' in frame[:64]:
                    break
        assert seen[0] == sent
        # traced against a trace-ignorant replica: stream still works,
        # the done frame just lacks the echo
        frames = list(lm_service.generate_request(
            "127.0.0.1", port, tokens=[1], max_new_tokens=1,
            trace=tracectx.TraceContext("ff" * 8),
        ))
        assert frames[-1]["stream"] == "done"
        assert "trace_id" not in frames[-1]
        ctrl = protocol.parse_ctrl(seen[1])
        assert ctrl["trace"]["id"] == "ff" * 8  # context DID travel
    finally:
        stop.set()
        rep_listener.close()
        client_listener.close()


def test_torn_trace_envelope_refused_cleanly():
    """A torn binary-payload envelope gets an explicit error frame from
    both the router and a replica server — never half-parsed bytes."""
    from distribuuuu_tpu.serve.fleet.router import Router

    router = Router()
    resp = json.loads(router.dispatch(tracectx.TRACE_MAGIC + b"\xff\xff"))
    assert resp["error"] == "bad_trace_envelope"


# --------------------------------------------------- engine-side span tree


def test_engine_spans_attribute_residency_and_unify_request_id(f32,
                                                               tmp_path):
    """Traced engine submissions: the trace id IS the request id
    (satellite 1); queue/prefill/decode spans parent onto the
    engine.request span; wall-clock residency makes the stage sum track
    the request's engine latency; untraced co-residents emit nothing."""
    from distribuuuu_tpu.telemetry import close_telemetry, setup_telemetry

    eng = _tiny_engine().start()
    rng = np.random.default_rng(7)
    setup_telemetry(str(tmp_path / "telemetry"), rank=0)
    try:
        ctx = tracectx.TraceContext(tracectx.new_trace_id(), "edge-span")
        traced = eng.submit(
            rng.integers(0, 256, (4,)).astype(np.int32),
            max_new_tokens=4, trace=ctx,
        )
        plain = eng.submit(
            rng.integers(0, 256, (4,)).astype(np.int32), max_new_tokens=4
        )
        traced.result(timeout=120.0)
        plain.result(timeout=120.0)
        assert traced.request_id == ctx.trace_id
        assert plain.request_id != ctx.trace_id
        eng.drain()
    finally:
        close_telemetry()

    _tools()
    import trace_request

    traces = trace_request.collect_traces(str(tmp_path))
    assert set(traces) == {ctx.trace_id}  # untraced neighbor: silent
    spans_ = traces[ctx.trace_id]
    root = next(s for s in spans_ if s["name"] == "engine.request")
    assert root["parent"] == "edge-span"
    assert root["new_tokens"] == 4 and root["length_class"]
    for s in spans_:
        if s["name"] in ("queue_wait", "prefill", "decode_step"):
            assert s["parent"] == root["span"]
    sh = trace_request.stage_shares(spans_)
    assert sh["total_source"] == "engine.request"
    # residency attribution: stages cover most of the engine wall
    assert 0.2 <= sh["stage_sum_ms"] / sh["total_ms"] <= 1.2
    assert sh["length_class"] == root["length_class"]
    bd = trace_request.breakdown_by_class(traces)
    assert bd[root["length_class"]]["requests"] == 1


# ------------------------------------------------- the committed artifact


def _artifact():
    path = os.path.join(REPO, "TRACE_r01.json")
    with open(path) as f:
        return json.load(f)


def test_committed_trace_artifact_names_exemplars():
    """TRACE_r01.json: a real 2-replica fleet under campaign traffic
    raised at least one p99 breach that NAMES its worst traced requests,
    and every named exemplar resolves to a captured trace."""
    art = _artifact()
    assert art["ok"] is True
    assert art["fleet"]["replicas"] == 2
    breaches = [
        a for a in art["alerts"]
        if a["rule"] in ("p99-breach", "backpressure")
        and a.get("exemplar_trace_ids")
    ]
    assert breaches, "no exemplar-named breach in the artifact"
    captured = set(art["traces"])
    for a in breaches:
        assert 1 <= len(a["exemplar_trace_ids"]) <= 3
        assert set(a["exemplar_trace_ids"]) <= captured


def test_committed_trace_artifact_waterfall_is_complete():
    """The exemplar trace renders as a complete waterfall: a connected
    tree whose stage spans sum to the router-observed latency within the
    pinned tolerance, and the traced run served outputs bit-identical
    to the untraced control."""
    art = _artifact()
    ex = art["exemplar"]
    assert ex["connected"] is True
    assert ex["shares"]["total_source"] == "router.dispatch"
    ratio = ex["shares"]["stage_sum_ms"] / ex["shares"]["total_ms"]
    assert art["stage_sum_tolerance"][0] <= ratio \
        <= art["stage_sum_tolerance"][1]
    assert set(ex["span_names"]) >= {
        "client.request", "router.dispatch", "engine.request",
        "queue_wait", "decode_step",
    }
    assert art["identity"]["traced_equals_untraced"] is True
    assert art["identity"]["requests_compared"] >= 1
    # per-span overhead stays under the 500µs ceiling (PERF.md pin)
    assert 0 < art["overhead"]["per_span_us"] < 500.0
