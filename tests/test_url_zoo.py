"""Pretrained URL zoo (utils/url_zoo.py — VERDICT r4 "What's missing" #2:
the reference auto-downloads torchvision weights on MODEL.PRETRAINED True,
ref: resnet.py:23-33). There is no up-front connectivity probe (ADVICE r5):
fetch() attempts the download and maps network-unreachable errors to the
actionable offline message. The build environment has zero egress, so both
paths are exercised with a mocked urlopen."""

import io
import os

import pytest

from distribuuuu_tpu.utils import url_zoo


@pytest.fixture()
def tmp_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("DISTRIBUUUU_CACHE", str(tmp_path / "zoo"))
    return tmp_path / "zoo"


def test_unknown_arch_raises(tmp_cache):
    with pytest.raises(ValueError, match="no pretrained-URL zoo entry"):
        url_zoo.fetch("vit_tiny")  # extension arch: no torchvision zoo URL


def test_unreachable_network_raises_actionable_error(tmp_cache, monkeypatch):
    """DNS failure / refused connection / timeout during the download map
    to the offline message — the attempt itself is the probe."""
    import urllib.error

    def raise_unreachable(url, timeout=None):
        raise urllib.error.URLError(OSError("Name or service not known"))

    monkeypatch.setattr(url_zoo.urllib.request, "urlopen", raise_unreachable)
    with pytest.raises(ValueError, match="MODEL.WEIGHTS pointing at"):
        url_zoo.fetch("resnet18")
    # no partial file left behind
    d = url_zoo.cache_dir()
    assert not (os.path.isdir(d) and os.listdir(d))


def test_http_error_is_download_failure_not_offline(tmp_cache, monkeypatch):
    """An HTTP error is a server RESPONSE (network reachable): report a
    failed download, not the offline message."""
    import urllib.error

    def raise_404(url, timeout=None):
        raise urllib.error.HTTPError(url, 404, "not found", {}, None)

    monkeypatch.setattr(url_zoo.urllib.request, "urlopen", raise_404)
    with pytest.raises(ValueError, match="downloading .* failed"):
        url_zoo.fetch("resnet18")


def test_download_and_cache(tmp_cache, monkeypatch):
    payload = b"fake-torch-pickle-bytes"
    calls = []

    class FakeResponse(io.BytesIO):
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    def fake_urlopen(url, timeout=None):
        calls.append(url)
        return FakeResponse(payload)

    monkeypatch.setattr(url_zoo, "_digest_ok", lambda *a: True)
    monkeypatch.setattr(
        url_zoo.urllib.request, "urlopen", fake_urlopen
    )
    path = url_zoo.fetch("resnet18")
    assert os.path.exists(path)
    with open(path, "rb") as f:
        assert f.read() == payload
    assert calls == [url_zoo.MODEL_URLS["resnet18"]]

    # second fetch: served from cache, no network call
    calls.clear()
    assert url_zoo.fetch("resnet18") == path
    assert calls == []


def test_every_zoo_arch_is_registered():
    from distribuuuu_tpu import models

    for arch in url_zoo.MODEL_URLS:
        assert arch in models.available_models(), arch


def test_digest_check(tmp_path):
    """_digest_ok verifies the sha256 prefix torchvision embeds in the
    filename; a truncated/corrupted file is rejected."""
    import hashlib

    p = tmp_path / "w.bin"
    p.write_bytes(b"weights-payload")
    good = hashlib.sha256(b"weights-payload").hexdigest()[:8]
    assert url_zoo._digest_ok(str(p), f"https://x/model-{good}.pth")
    assert not url_zoo._digest_ok(str(p), "https://x/model-00000000.pth")
    # no embedded digest -> accepted
    assert url_zoo._digest_ok(str(p), "https://x/model.pth")


def test_full_digest_pin_is_authoritative(tmp_path):
    """ADVICE r5: when a full 64-hex pin exists (MODEL_SHA256 or an
    explicit sidecar pin) the COMPLETE hash is compared — the 32-bit
    filename prefix is neither sufficient (wrong tail ⇒ reject) nor
    necessary (pin match ⇒ accept even when the prefix disagrees)."""
    import hashlib

    payload = b"weights-payload"
    p = tmp_path / "w.bin"
    p.write_bytes(payload)
    full = hashlib.sha256(payload).hexdigest()
    prefix_url = f"https://x/model-{full[:8]}.pth"

    # prefix matches but the full pin has a different tail: rejected
    forged = full[:8] + "0" * 56
    assert not url_zoo._digest_ok(str(p), prefix_url, pin=forged)
    # full pin matches while the filename prefix does NOT: accepted
    assert url_zoo._digest_ok(str(p), "https://x/model-00000000.pth", pin=full)
    # MODEL_SHA256 table drives the same comparison per arch
    try:
        url_zoo.MODEL_SHA256["resnet18"] = full
        assert url_zoo._digest_ok(str(p), "https://x/model-00000000.pth",
                                  arch="resnet18")
        url_zoo.MODEL_SHA256["resnet18"] = forged
        assert not url_zoo._digest_ok(str(p), prefix_url, arch="resnet18")
    finally:
        url_zoo.MODEL_SHA256.pop("resnet18", None)


def test_sidecar_pin_verifies_cache_with_complete_hash(tmp_cache, monkeypatch):
    """A verified download records its full sha256 in a ``.sha256``
    sidecar; later cache hits verify the COMPLETE hash against it, so
    cache tampering is caught (and triggers a re-download) even for a URL
    with no filename-embedded digest, where the old prefix-only check had
    nothing to verify."""
    import io

    payload = b"real-zoo-weights"
    calls = []

    class FakeResponse(io.BytesIO):
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    def fake_urlopen(url, timeout=None):
        calls.append(url)
        return FakeResponse(payload)

    monkeypatch.setitem(
        url_zoo.MODEL_URLS, "resnet18", "https://x/model.pth"
    )  # no embedded digest → only the full-hash sidecar protects the cache
    monkeypatch.setattr(url_zoo.urllib.request, "urlopen", fake_urlopen)

    path = url_zoo.fetch("resnet18")
    assert len(calls) == 1
    assert url_zoo._read_pin(path) == url_zoo._sha256(path)

    # clean cache hit: full-hash pin verifies, no network call
    assert url_zoo.fetch("resnet18") == path
    assert len(calls) == 1

    # tamper the cached pickle (prefix-less URL: undetectable pre-sidecar)
    with open(path, "ab") as f:
        f.write(b"tampered")
    assert url_zoo.fetch("resnet18") == path
    assert len(calls) == 2  # mismatch detected → re-downloaded
    with open(path, "rb") as f:
        assert f.read() == payload


def test_download_failing_digest_raises(tmp_cache, monkeypatch):
    import io

    class FakeResponse(io.BytesIO):
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    monkeypatch.setattr(
        url_zoo.urllib.request, "urlopen",
        lambda url, timeout=None: FakeResponse(b"truncated"),
    )
    with pytest.raises(ValueError, match="checksum"):
        url_zoo.fetch("resnet18")
    # no partial/corrupt file installed in the cache
    import os as _os

    assert not any(
        f for f in (_os.listdir(url_zoo.cache_dir())
                    if _os.path.isdir(url_zoo.cache_dir()) else [])
        if not f.endswith(".part")
    )
