"""Connectivity-guarded pretrained URL zoo (utils/url_zoo.py — VERDICT r4
"What's missing" #2: the reference auto-downloads torchvision weights on
MODEL.PRETRAINED True, ref: resnet.py:23-33). The build environment has
zero egress, so the download path is exercised with a mocked urlopen and
the refusal path both mocked and for real."""

import io
import os

import pytest

from distribuuuu_tpu.utils import url_zoo


@pytest.fixture()
def tmp_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("DISTRIBUUUU_CACHE", str(tmp_path / "zoo"))
    return tmp_path / "zoo"


def test_unknown_arch_raises(tmp_cache):
    with pytest.raises(ValueError, match="no pretrained-URL zoo entry"):
        url_zoo.fetch("vit_tiny")  # extension arch: no torchvision zoo URL


def test_offline_raises_actionable_error(tmp_cache, monkeypatch):
    monkeypatch.setattr(url_zoo, "_online", lambda: False)
    with pytest.raises(ValueError, match="MODEL.WEIGHTS pointing at"):
        url_zoo.fetch("resnet18")


def test_download_and_cache(tmp_cache, monkeypatch):
    payload = b"fake-torch-pickle-bytes"
    calls = []

    class FakeResponse(io.BytesIO):
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    def fake_urlopen(url, timeout=None):
        calls.append(url)
        return FakeResponse(payload)

    monkeypatch.setattr(url_zoo, "_online", lambda: True)
    monkeypatch.setattr(
        url_zoo.urllib.request, "urlopen", fake_urlopen
    )
    path = url_zoo.fetch("resnet18")
    assert os.path.exists(path)
    with open(path, "rb") as f:
        assert f.read() == payload
    assert calls == [url_zoo.MODEL_URLS["resnet18"]]

    # second fetch: served from cache, no network call
    calls.clear()
    assert url_zoo.fetch("resnet18") == path
    assert calls == []


def test_real_probe_is_offline_here():
    """This environment has zero egress: the real probe must say offline
    (and complete within its timeout rather than hanging)."""
    assert url_zoo._online() is False


def test_every_zoo_arch_is_registered():
    from distribuuuu_tpu import models

    for arch in url_zoo.MODEL_URLS:
        assert arch in models.available_models(), arch
