"""Connectivity-guarded pretrained URL zoo (utils/url_zoo.py — VERDICT r4
"What's missing" #2: the reference auto-downloads torchvision weights on
MODEL.PRETRAINED True, ref: resnet.py:23-33). The build environment has
zero egress, so the download path is exercised with a mocked urlopen and
the refusal path both mocked and for real."""

import io
import os

import pytest

from distribuuuu_tpu.utils import url_zoo


@pytest.fixture()
def tmp_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("DISTRIBUUUU_CACHE", str(tmp_path / "zoo"))
    return tmp_path / "zoo"


def test_unknown_arch_raises(tmp_cache):
    with pytest.raises(ValueError, match="no pretrained-URL zoo entry"):
        url_zoo.fetch("vit_tiny")  # extension arch: no torchvision zoo URL


def test_offline_raises_actionable_error(tmp_cache, monkeypatch):
    monkeypatch.setattr(url_zoo, "_online", lambda: False)
    with pytest.raises(ValueError, match="MODEL.WEIGHTS pointing at"):
        url_zoo.fetch("resnet18")


def test_download_and_cache(tmp_cache, monkeypatch):
    payload = b"fake-torch-pickle-bytes"
    calls = []

    class FakeResponse(io.BytesIO):
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    def fake_urlopen(url, timeout=None):
        calls.append(url)
        return FakeResponse(payload)

    monkeypatch.setattr(url_zoo, "_online", lambda: True)
    monkeypatch.setattr(url_zoo, "_digest_ok", lambda *a: True)
    monkeypatch.setattr(
        url_zoo.urllib.request, "urlopen", fake_urlopen
    )
    path = url_zoo.fetch("resnet18")
    assert os.path.exists(path)
    with open(path, "rb") as f:
        assert f.read() == payload
    assert calls == [url_zoo.MODEL_URLS["resnet18"]]

    # second fetch: served from cache, no network call
    calls.clear()
    assert url_zoo.fetch("resnet18") == path
    assert calls == []


def test_real_probe_terminates():
    """The real probe must return a bool within its timeout on ANY host —
    offline (this zero-egress build environment) or online (a developer
    laptop) — rather than hanging or raising."""
    import time

    t0 = time.monotonic()
    result = url_zoo._online()
    assert isinstance(result, bool)
    assert time.monotonic() - t0 < url_zoo._PROBE_TIMEOUT_S + 5


def test_every_zoo_arch_is_registered():
    from distribuuuu_tpu import models

    for arch in url_zoo.MODEL_URLS:
        assert arch in models.available_models(), arch


def test_digest_check(tmp_path):
    """_digest_ok verifies the sha256 prefix torchvision embeds in the
    filename; a truncated/corrupted file is rejected."""
    import hashlib

    p = tmp_path / "w.bin"
    p.write_bytes(b"weights-payload")
    good = hashlib.sha256(b"weights-payload").hexdigest()[:8]
    assert url_zoo._digest_ok(str(p), f"https://x/model-{good}.pth")
    assert not url_zoo._digest_ok(str(p), "https://x/model-00000000.pth")
    # no embedded digest -> accepted
    assert url_zoo._digest_ok(str(p), "https://x/model.pth")


def test_download_failing_digest_raises(tmp_cache, monkeypatch):
    import io

    class FakeResponse(io.BytesIO):
        def __enter__(self):
            return self

        def __exit__(self, *a):
            return False

    monkeypatch.setattr(url_zoo, "_online", lambda: True)
    monkeypatch.setattr(
        url_zoo.urllib.request, "urlopen",
        lambda url, timeout=None: FakeResponse(b"truncated"),
    )
    with pytest.raises(ValueError, match="checksum"):
        url_zoo.fetch("resnet18")
    # no partial/corrupt file installed in the cache
    import os as _os

    assert not any(
        f for f in (_os.listdir(url_zoo.cache_dir())
                    if _os.path.isdir(url_zoo.cache_dir()) else [])
        if not f.endswith(".part")
    )
