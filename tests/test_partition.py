"""Unit tier of the partition layer (parallel/partition/, ISSUE 9):
spec-table algebra, topology registry validation/classification, and the
generated-sweep containment of the legacy dryrun matrix."""

import os
import sys

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer
from distribuuuu_tpu.parallel import mesh as mesh_lib
from distribuuuu_tpu.parallel.partition import specs, topology

TOOLS = os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")


# ------------------------------------------------------------- spec table


def test_spec_table_unknown_leaf_refused():
    table = specs.SpecTable(
        rules=(specs.SpecRule(r"kernel$", P(None, "model")),), strict=True
    )
    assert table.spec_for("/Dense_0/kernel") == P(None, "model")
    with pytest.raises(specs.UnknownLeafError, match="no spec rule covers"):
        table.spec_for("/Dense_0/bias")


def test_spec_table_default_when_not_strict():
    table = specs.SpecTable(
        rules=(specs.SpecRule(r"kernel$", P(None, "model")),), default=P()
    )
    assert table.spec_for("/whatever") == P()


def test_batch_table_covers_loader_keys_and_refuses_strangers():
    for key in ("image", "label", "mask"):
        assert specs.BATCH_TABLE.spec_for(f"['{key}']") == P("data")
    with pytest.raises(specs.UnknownLeafError):
        specs.BATCH_TABLE.spec_for("['surprise_key']")
    # fold/accum stacking shifts the batch dim right
    assert specs.batch_spec("image", leading_dims=2) == P(None, None, "data")


def test_validate_leaf_spec_conflicting_axes():
    sizes = {"data": 4, "model": 2}
    # same axis on two dims
    with pytest.raises(specs.SpecConflictError, match="at most one dim"):
        specs.validate_leaf_spec(
            "/w", P("data", ("model", "data")), (8, 8), sizes
        )
    # more entries than dims
    with pytest.raises(specs.SpecConflictError, match="rank"):
        specs.validate_leaf_spec("/w", P("data", None, None), (8, 8), sizes)
    # unknown axis
    with pytest.raises(specs.SpecConflictError, match="does not exist"):
        specs.validate_leaf_spec("/w", P("bogus"), (8,), sizes)
    # clean specs pass; a non-divisible extent is LEGAL (GSPMD pads it —
    # e.g. a 10-class head kernel on a 4-way model axis)
    specs.validate_leaf_spec("/w", P(None, ("model", "data")), (3, 8), sizes)
    specs.validate_leaf_spec("/w", P("data"), (6, 8), sizes)


def test_collapse_unit_axes_to_replication():
    # a size-1 axis shards nothing: the TP annotation IS replication on a
    # dp-only mesh
    assert specs.collapse_unit_axes(
        P(None, "model"), {"model": 1, "data": 8}
    ) == P(None, None)
    assert specs.canonicalize(
        P(None, "model"), {"model": 1, "data": 8}
    ) == P()
    # mixed tuple entry: the unit axis drops out of the tuple
    assert specs.collapse_unit_axes(
        P(("model", "data")), {"model": 1, "data": 8}
    ) == P("data")
    # populated axes survive canonicalization
    assert specs.canonicalize(
        P("data", None, "model"), {"model": 2, "data": 4}
    ) == P("data", None, "model")


# -------------------------------------------------------- topology registry


def test_from_cfg_resolves_wildcards_and_classifies():
    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    topo = topology.from_cfg(cfg, n_devices=8)
    assert topo.axes == {
        "data": 8, "model": 1, "seq": 1, "pipe": 1, "expert": 1
    }
    assert topo.class_name() == "dp8"
    cfg.MESH.DATA, cfg.MESH.MODEL, cfg.MESH.ZERO = -1, 2, 1
    topo = topology.from_cfg(cfg, n_devices=8)
    assert (topo.data, topo.model, topo.zero) == (4, 2, 1)
    assert topo.class_name() == "dp4·tp2·zero1"
    assert topo.describe()["features"] == ["dp", "tp", "zero1"]


def test_registry_refuses_invalid_stanzas():
    config.reset_cfg()
    cases = [
        # (overrides, error fragment)
        ({"MESH.ZERO": 2}, "stage 2 is"),
        ({"MODEL.ARCH": "resnet18", "MESH.PIPE": 2}, "uniform-stage"),
        ({"MODEL.ARCH": "resnet18", "MESH.SEQ": 2}, "MESH.SEQ"),
        ({"MODEL.ARCH": "vit_tiny", "MESH.PIPE": 2, "MESH.SEQ": 2},
         "does not compose with the pipe axis"),
        ({"MODEL.ARCH": "vit_tiny", "MESH.EXPERT": 2}, "only the \\*_moe"),
        ({"MODEL.ARCH": "vit_tiny_moe", "MESH.EXPERT": 8,
          "MODEL.MOE.NUM_EXPERTS": 6}, "must divide MODEL.MOE.NUM_EXPERTS"),
        ({"MODEL.ARCH": "vit_tiny", "MESH.PIPE": 8}, "not divisible by"),
    ]
    for overrides, frag in cases:
        config.reset_cfg()
        flat = [x for kv in overrides.items() for x in kv]
        cfg.merge_from_list(list(map(str, flat)))
        with pytest.raises(ValueError, match=frag):
            topology.from_cfg(cfg, n_devices=8)
    config.reset_cfg()


def test_zero3_under_pp_and_three_axis_ep_now_validate():
    """The ISSUE 9 acceptance stanzas — refused or pathless before r11 —
    must pass the registry."""
    config.reset_cfg()
    cfg.MODEL.ARCH = "vit_tiny"
    cfg.MESH.DATA, cfg.MESH.PIPE, cfg.MESH.ZERO = 2, 4, 3
    topo = topology.from_cfg(cfg, n_devices=8)
    assert set(topo.describe()["features"]) == {"dp", "pp", "zero3"}
    config.reset_cfg()
    cfg.MODEL.ARCH = "vit_tiny_moe"
    cfg.MESH.DATA = cfg.MESH.MODEL = cfg.MESH.EXPERT = 2
    cfg.MESH.ZERO = 1
    topo = topology.from_cfg(cfg, n_devices=8)
    assert set(topo.describe()["features"]) == {"dp", "tp", "ep", "zero1"}
    assert topo.moe_axis() == "expert"


def test_check_trainer_mesh_delegates_to_registry():
    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    topo = trainer.check_trainer_mesh()
    assert topo.class_name() == "dp8"


def test_enumeration_contains_legacy_matrix():
    """Every case the pre-r11 dryrun hand-enumerated appears in the
    generated sweep (the ISSUE 9 satellite's containment contract)."""
    sys.path.insert(0, TOOLS)
    try:
        import mesh_sweep
    finally:
        sys.path.remove(TOOLS)

    cases = mesh_sweep.generate_cases(8)
    keys = {
        mesh_sweep._case_key(c["axes"], c["zero"], c["arch"]) for c in cases
    }
    for legacy in mesh_sweep.legacy_matrix(8):
        k = mesh_sweep._case_key(
            legacy["axes"], legacy["zero"], legacy["arch"]
        )
        assert k in keys, f"legacy case missing from generated set: {legacy}"
    # ... and the acceptance compositions ride as CORE cases
    core = {c["name"] for c in cases if c["tier"] == "core"}
    assert "dp2·pp4·zero3[vit_tiny]" in core
    assert "dp2·tp2·ep2·zero1[vit_tiny_moe]" in core
    # legacy ride-along variants survive as generated extras
    by_name = {c["name"]: c for c in cases}
    assert "fold_accum" in by_name["dp4·tp2[resnet18]"]["extras"]
    assert "aux_check" in by_name["dp2·tp2·pp2[vit_tiny_moe]"]["extras"]
    assert "flash" in by_name["dp2·pp4[vit_tiny]"]["extras"]


def test_classify_transition_details_axis_moves():
    a = topology.Topology(data=4, model=2, zero=1).describe()
    b = topology.Topology(data=2, model=2, zero=1).describe()
    kind, detail = topology.classify_transition(a, b)
    assert kind == "reshardable"
    assert "data 4→2" in detail and "dp4·tp2·zero1→dp2·tp2·zero1" in detail
    assert topology.classify_transition(a, a) == ("exact", "")
    kind, detail = topology.classify_transition(
        topology.Topology(data=8).describe(),
        topology.Topology(data=8, zero=3).describe(),
    )
    assert kind == "reshardable" and "zero 0→3" in detail


# -------------------------------------------------- layout via the spec layer


def test_state_layout_matches_trainer_delegation():
    """trainer._state_layout IS the partition spec layer now — one
    resolver; the layouts agree leaf for leaf."""
    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MESH.ZERO = 1
    mesh = mesh_lib.mesh_from_cfg(cfg)
    model = trainer.build_model_from_cfg()
    a = trainer._state_layout(model, mesh, 32)
    b = specs.state_layout(model, mesh, 32, zero_stage=1)
    for key in ("params", "opt", "grads"):
        la, lb = jax.tree.leaves(a[key]), jax.tree.leaves(b[key])
        assert len(la) == len(lb)
        assert all(x == y for x, y in zip(la, lb))
    # the ZeRO transform added exactly the data axis
    assert specs.added_axes(b) == ("data",)


def test_state_layout_validates_derived_specs():
    """A malformed derivation cannot reach GSPMD: validation raises with
    the leaf path."""
    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    mesh = mesh_lib.mesh_from_cfg(cfg)
    model = trainer.build_model_from_cfg()
    layout = specs.state_layout(model, mesh, 32, zero_stage=0)
    # sanity: the base layout is fully replicated over data at rest
    for leaf in jax.tree.leaves(layout["params"]):
        assert "data" not in specs.spec_axes(leaf.spec)


def test_mesh_expert_axis_exists_and_collapses():
    """The new expert axis is first-class on every mesh and inert at
    size 1 (axis-size-1 collapse: existing topologies see no change)."""
    mesh = mesh_lib.build_mesh()
    assert dict(mesh.shape)["expert"] == 1
    assert mesh_lib.MESH_AXES == ("data", "model", "seq", "pipe", "expert")
    sizes = mesh_lib.resolve_axis_sizes([-1, 2, 1, 1, 2], 8)
    assert sizes == [2, 2, 1, 1, 2]
    with pytest.raises(ValueError, match="do not divide"):
        mesh_lib.resolve_axis_sizes([3, 1, 1, 1, 1], 8)
