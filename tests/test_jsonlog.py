"""Structured JSONL metrics sink (utils/jsonlog.py) — machine-readable
observability next to the reference-style text logs (SURVEY.md §5.5)."""

import json
import os

import numpy as np
import pytest

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.utils import jsonlog


@pytest.fixture(autouse=True)
def _close_sink():
    yield
    jsonlog.close_metrics_log()


def test_noop_before_setup():
    jsonlog.metrics_log("train", loss=1.0)  # must not raise


def test_records_are_one_json_per_line(tmp_path):
    jsonlog.setup_metrics_log(str(tmp_path))
    jsonlog.metrics_log("train", epoch=1, loss=2.5)
    jsonlog.metrics_log("eval", epoch=1, top1=10.0)
    jsonlog.close_metrics_log()
    lines = open(tmp_path / "metrics.jsonl").read().splitlines()
    recs = [json.loads(ln) for ln in lines]
    assert [r["kind"] for r in recs] == ["train", "eval"]
    assert recs[0]["loss"] == 2.5 and recs[1]["top1"] == 10.0
    assert all("t" in r for r in recs)


def test_non_primary_is_silent(tmp_path):
    jsonlog.setup_metrics_log(str(tmp_path), primary=False)
    jsonlog.metrics_log("train", loss=1.0)
    assert not os.path.exists(tmp_path / "metrics.jsonl")


@pytest.mark.slow
def test_train_model_writes_metrics(tmp_path):
    from distribuuuu_tpu import trainer

    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.DUMMY_INPUT = True
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.TRAIN.BATCH_SIZE = 2
    cfg.TRAIN.IM_SIZE = 32
    cfg.TRAIN.PRINT_FREQ = 4
    cfg.TEST.BATCH_SIZE = 4
    cfg.TEST.IM_SIZE = 32
    cfg.OPTIM.MAX_EPOCH = 1
    # trivial dummy task at LR 0.1 can saturate to inf logits by epoch end
    # (loss 0 → weight blowup → NaN); damp — the sink, not SGD, is on test
    cfg.OPTIM.BASE_LR = 0.01
    cfg.OUT_DIR = str(tmp_path)
    cfg.RNG_SEED = 0
    trainer.train_model()
    jsonlog.close_metrics_log()
    recs = [
        json.loads(ln)
        for ln in open(tmp_path / "metrics.jsonl").read().splitlines()
    ]
    kinds = [r["kind"] for r in recs]
    assert "train" in kinds and "eval" in kinds and "epoch" in kinds
    train_recs = [r for r in recs if r["kind"] == "train"]
    assert all(
        np.isfinite(r["loss"]) and r["epoch"] == 1 for r in train_recs
    )
    epoch_rec = [r for r in recs if r["kind"] == "epoch"][-1]
    assert epoch_rec["acc1"] == epoch_rec["best_acc1"]
