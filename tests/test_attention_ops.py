"""Relative-position attention op tests against brute-force index oracles.

The pad-reshape rel→abs trick (ref math: /root/reference/distribuuuu/models/
botnet.py:25-57) is checked against direct gather indexing, which is an
independent derivation: abs[i, j] = rel[i, (j - i) + (L-1)].
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distribuuuu_tpu.ops.attention import (
    abs_pos_logits,
    mhsa_2d,
    rel_pos_logits,
    rel_to_abs,
    relative_logits_1d,
)


def test_rel_to_abs_against_gather():
    rng = np.random.default_rng(0)
    B, N, L = 2, 3, 5
    rel = rng.normal(size=(B, N, L, 2 * L - 1)).astype(np.float32)
    out = np.asarray(rel_to_abs(jnp.asarray(rel)))
    expected = np.zeros((B, N, L, L), np.float32)
    for i in range(L):
        for j in range(L):
            expected[:, :, i, j] = rel[:, :, i, (j - i) + (L - 1)]
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_relative_logits_1d_shapes_and_broadcast():
    rng = np.random.default_rng(1)
    B, N, H, W, d = 2, 2, 3, 4, 6
    q = rng.normal(size=(B, N, H, W, d)).astype(np.float32)
    rel_k = rng.normal(size=(2 * W - 1, d)).astype(np.float32)
    out = np.asarray(relative_logits_1d(jnp.asarray(q), jnp.asarray(rel_k)))
    assert out.shape == (B, N, H, H, W, W)
    # broadcast over the expanded (key-row) axis: identical for all key rows
    np.testing.assert_allclose(out[:, :, :, 0], out[:, :, :, 1], rtol=1e-6)
    # and each (query row, query col, key col) value = q · rel_k[rel index]
    for y in range(W):
        for j in range(W):
            expected = q[:, :, :, y, :] @ rel_k[(j - y) + (W - 1)]
            # atol floor: with no atol, a near-zero dot product turns fp32
            # rounding (~1e-8 abs) into an rtol violation — XLA CPU's
            # einsum reassociation drifts exactly one such element
            np.testing.assert_allclose(
                out[:, :, :, 0, y, j], expected, rtol=1e-5, atol=1e-6
            )


def test_rel_pos_logits_decomposes_into_row_and_col_terms():
    """Full 2D logits must equal width-term + height-term computed by brute
    force over absolute positions."""
    rng = np.random.default_rng(2)
    B, N, H, W, d = 1, 2, 3, 3, 4
    q = rng.normal(size=(B, N, H * W, d)).astype(np.float32)
    rel_h = rng.normal(size=(2 * H - 1, d)).astype(np.float32)
    rel_w = rng.normal(size=(2 * W - 1, d)).astype(np.float32)
    out = np.asarray(
        rel_pos_logits(jnp.asarray(q), jnp.asarray(rel_h), jnp.asarray(rel_w), H, W)
    )
    q4 = q.reshape(B, N, H, W, d)
    expected = np.zeros((B, N, H * W, H * W), np.float32)
    for qx in range(H):
        for qy in range(W):
            for kx in range(H):
                for ky in range(W):
                    qi, ki = qx * W + qy, kx * W + ky
                    expected[:, :, qi, ki] = (
                        q4[:, :, qx, qy, :] @ rel_w[(ky - qy) + (W - 1)]
                        + q4[:, :, qx, qy, :] @ rel_h[(kx - qx) + (H - 1)]
                    )
    np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)


def test_abs_pos_logits():
    rng = np.random.default_rng(3)
    B, N, H, W, d = 2, 2, 2, 3, 4
    q = rng.normal(size=(B, N, H * W, d)).astype(np.float32)
    eh = rng.normal(size=(H, d)).astype(np.float32)
    ew = rng.normal(size=(W, d)).astype(np.float32)
    out = np.asarray(abs_pos_logits(jnp.asarray(q), jnp.asarray(eh), jnp.asarray(ew)))
    emb = (eh[:, None, :] + ew[None, :, :]).reshape(H * W, d)
    expected = np.einsum("bnid,jd->bnij", q, emb)
    np.testing.assert_allclose(out, expected, rtol=1e-5)


def test_mhsa_matches_plain_softmax_attention():
    rng = np.random.default_rng(4)
    B, N, L, d = 2, 2, 6, 4
    q = rng.normal(size=(B, N, L, d)).astype(np.float32)
    k = rng.normal(size=(B, N, L, d)).astype(np.float32)
    v = rng.normal(size=(B, N, L, d)).astype(np.float32)
    pos = rng.normal(size=(B, N, L, L)).astype(np.float32)
    scale = d ** -0.5
    out = np.asarray(mhsa_2d(*map(jnp.asarray, (q, k, v, pos)), scale))
    logits = np.einsum("bnxd,bnyd->bnxy", q * scale, k) + pos
    w = np.exp(logits - logits.max(-1, keepdims=True))
    w /= w.sum(-1, keepdims=True)
    expected = np.einsum("bnxy,bnyd->bnxd", w, v)
    np.testing.assert_allclose(out, expected, rtol=1e-4, atol=1e-5)


def test_botnet_mhsa_module_runs_under_jit():
    from distribuuuu_tpu.models.botnet import MHSA2D

    m = MHSA2D(fmap_size=(4, 4), heads=2, dim_qk=8, dim_v=8, dtype=jnp.float32)
    x = jnp.ones((2, 4, 4, 16))
    v = m.init(jax.random.key(0), x)
    out = jax.jit(lambda v, x: m.apply(v, x))(v, x)
    assert out.shape == (2, 4, 4, 16)
    # wrong grid must fail loudly (ref hard-assert: botnet.py:270-271)
    with pytest.raises(AssertionError):
        m.apply(v, jnp.ones((2, 5, 5, 16)))
