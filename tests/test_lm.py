"""LM workload plane (ISSUE 12): decoder-only GPT on the partition layer,
KV-cache generation pinned against the teacher-forced forward, continuous
batching under ragged completions, the streaming serve protocol, and the
telemetry/config satellites."""

import glob
import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import models


def _tiny_gpt(seq_len=32, vocab=320, dtype=jnp.float32, **kw):
    from distribuuuu_tpu.models.gpt import GPT

    return GPT(
        vocab_size=vocab, seq_len=seq_len, dim=32, depth=2, num_heads=2,
        dtype=dtype, **kw,
    )


def _params(model, key=0):
    return model.init(
        jax.random.key(key), model.dummy_input(), train=False
    )["params"]


@pytest.fixture()
def f32(monkeypatch):
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    yield


# ------------------------------------------------------------------ model


def test_gpt_forward_shape_and_registry(f32):
    model = models.build_model("gpt_nano", num_classes=320, seq_len=16,
                               dtype=jnp.float32)
    toks = jnp.zeros((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), toks, train=False)["params"]
    logits = model.apply({"params": params}, toks, train=False)
    assert logits.shape == (2, 16, 320)
    assert "gpt_nano" in models.available_models()
    assert "gpt_nano_moe" in models.available_models()


def test_gpt_attention_is_causal(f32):
    """Changing token j must not move any logit at positions < j."""
    model = _tiny_gpt(seq_len=12)
    params = _params(model)
    rng = np.random.default_rng(0)
    a = rng.integers(0, 256, (1, 12)).astype(np.int32)
    b = a.copy()
    b[0, 7:] = (b[0, 7:] + 11) % 256  # perturb the tail only
    la = model.apply({"params": params}, jnp.asarray(a), train=False)
    lb = model.apply({"params": params}, jnp.asarray(b), train=False)
    np.testing.assert_allclose(la[0, :7], lb[0, :7], rtol=0, atol=0)
    assert not np.allclose(la[0, 7:], lb[0, 7:])


def test_gpt_shorter_input_slices_position_table(f32):
    model = _tiny_gpt(seq_len=16)
    params = _params(model)
    toks = jnp.zeros((1, 5), jnp.int32)
    assert model.apply(
        {"params": params}, toks, train=False
    ).shape == (1, 5, 320)
    with pytest.raises(ValueError, match="exceeds the trained context"):
        model.apply(
            {"params": params}, jnp.zeros((1, 17), jnp.int32), train=False
        )


def test_token_metrics_flatten(f32):
    """cross_entropy/accuracy over [B, S, V] == the flattened [B*S, V]
    computation — the task head IS the shared loss (no LM loss path)."""
    from distribuuuu_tpu.utils.metrics import accuracy, cross_entropy

    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.standard_normal((2, 5, 7)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 7, (2, 5)), jnp.int32)
    flat_l = logits.reshape(-1, 7)
    flat_t = labels.reshape(-1)
    np.testing.assert_allclose(
        float(cross_entropy(logits, labels)),
        float(cross_entropy(flat_l, flat_t)), rtol=1e-6,
    )
    a = accuracy(logits, labels, topk=(1, 3))
    b = accuracy(flat_l, flat_t, topk=(1, 3))
    np.testing.assert_allclose(
        [float(x) for x in a], [float(x) for x in b], rtol=1e-6
    )


def test_eval_step_counts_tokens(f32):
    """The one eval step generalizes per-token: count == mask · seq_len,
    masked-out (padded) sequences contribute nothing."""
    from distribuuuu_tpu.parallel.partition.lowering import (
        TrainState, make_eval_step,
    )

    model = _tiny_gpt(seq_len=8)
    params = _params(model)
    state = TrainState(params=params, batch_stats={}, opt_state=None,
                       step=jnp.int32(0), key=jax.random.key(0))
    step = make_eval_step(model, topk=5)
    rng = np.random.default_rng(2)
    batch = {
        "image": jnp.asarray(rng.integers(0, 256, (4, 8)), jnp.int32),
        "label": jnp.asarray(rng.integers(0, 256, (4, 8)), jnp.int32),
        "mask": jnp.asarray([1.0, 1.0, 1.0, 0.0], jnp.float32),
    }
    m = step(state, batch)
    assert float(m["count"]) == 3 * 8
    assert np.isfinite(float(m["loss_sum"]))


# ------------------------------------------------- KV-cache decode (pins)


def _engine(model, params, **kw):
    from distribuuuu_tpu.lm.generate import GenerateEngine

    kw.setdefault("prompt_len", 8)
    kw.setdefault("max_new_tokens", 6)
    kw.setdefault("batch_tiles", [2])
    kw.setdefault("cache_tiles", [16])
    return GenerateEngine(model, {"params": params}, **kw)


def test_kv_decode_logits_match_teacher_forced(f32):
    """THE acceptance pin: prefill + per-token decode logits equal the
    full teacher-forced forward at every position (within float
    tolerance), so the cache math is the training math."""
    model = _tiny_gpt(seq_len=32)
    params = _params(model)
    eng = _engine(model, params, batch_tiles=[1], cache_tiles=[32],
                  prompt_len=8, max_new_tokens=8)
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, 256, (6,)).astype(np.int32)
    # prefill: per-position logits over the prompt
    ptile = 8
    padded = np.zeros((1, ptile), np.int32)
    padded[0, :6] = prompt
    logits_pre, kv = eng._prefill_exec[ptile](eng._variables,
                                              jnp.asarray(padded))
    full = model.apply({"params": params}, jnp.asarray(prompt[None]),
                       train=False)
    np.testing.assert_allclose(
        np.asarray(logits_pre)[0, :6], np.asarray(full)[0], atol=1e-4,
    )
    # decode: one token at a time continues the same logits
    eng.start()
    out = eng.submit(prompt, max_new_tokens=8).result()
    seq = np.concatenate([prompt, out])
    tf = np.asarray(model.apply(
        {"params": params}, jnp.asarray(seq[None]), train=False,
    ))[0]
    # greedy from each teacher-forced position reproduces the decode
    for k, tok in enumerate(out):
        assert int(tf[len(prompt) - 1 + k].argmax()) == tok
    eng.drain()


def test_continuous_batching_ragged_completions_uncontaminated(f32):
    """Concurrent requests with ragged lengths/budgets produce EXACTLY
    the tokens each would produce alone (no cross-request logit
    contamination through the paged cache), and every request retires —
    zero drops."""
    model = _tiny_gpt(seq_len=32)
    params = _params(model)
    eng = _engine(model, params, batch_tiles=[1, 2, 4], cache_tiles=[16, 32],
                  prompt_len=8, max_new_tokens=8).start()
    rng = np.random.default_rng(4)
    subs = []
    for i in range(7):
        p = rng.integers(0, 256, (2 + i % 5,)).astype(np.int32)
        subs.append((p, eng.submit(p, max_new_tokens=2 + i % 6)))
    for p, stream in subs:
        got = stream.result(timeout=120.0)
        assert stream.reason in ("eos", "max_new_tokens", "cache_full")
        seq = list(p)
        for tok in got:  # isolated greedy reference
            lg = model.apply(
                {"params": params},
                jnp.asarray(np.asarray(seq)[None]), train=False,
            )
            assert tok == int(np.asarray(lg)[0, -1].argmax())
            seq.append(tok)
    st = eng.stats()
    assert st["requests"] == 7 and st["retired"] == 7
    assert st["queue_depth"] == 0 and st["active"] == 0
    eng.drain()


def test_moe_gpt_decode_matches_teacher_forced(f32):
    """The MoE LM decodes through MoeMlp's reference path — same pin."""
    model = _tiny_gpt(seq_len=16, moe_experts=4, moe_top_k=2)
    params = _params(model)
    eng = _engine(model, params, batch_tiles=[1], cache_tiles=[16],
                  prompt_len=4, max_new_tokens=4).start()
    prompt = np.asarray([10, 20, 30], np.int32)
    out = eng.submit(prompt).result()
    seq = list(prompt)
    for tok in out:
        lg = model.apply({"params": params},
                         jnp.asarray(np.asarray(seq)[None]), train=False)
        assert tok == int(np.asarray(lg)[0, -1].argmax())
        seq.append(tok)
    eng.drain()


def test_generate_config_validation(f32):
    from distribuuuu_tpu.lm.generate import validate_generate_cfg

    # cache tile cannot hold prompt + new tokens — message carries the sum
    with pytest.raises(ValueError, match=r"MAX_NEW_TOKENS=16 = 32"):
        validate_generate_cfg(64, 16, 16, [2], [24])
    # cache tile beyond the trained context
    with pytest.raises(ValueError, match="LM.SEQ_LEN"):
        validate_generate_cfg(32, 8, 8, [2], [64])
    bt, ct = validate_generate_cfg(64, 16, 16, [], [])
    assert bt == [1, 2, 4] and ct == [64]


def test_engine_tile_growth_and_stats(f32):
    """Admissions past the smallest tiles grow batch/cache tiles through
    the precompiled pads; stats expose the fleet warm-gate contract."""
    model = _tiny_gpt(seq_len=32)
    params = _params(model)
    eng = _engine(model, params, batch_tiles=[1, 2], cache_tiles=[16, 32],
                  prompt_len=8, max_new_tokens=12)
    st = eng.stats()
    assert st["n_compiles"] == eng.n_compiles > 0
    assert st["buckets"] == [[1, 16], [1, 32], [2, 16], [2, 32]]
    eng.start()
    rng = np.random.default_rng(5)
    streams = [
        eng.submit(rng.integers(0, 256, (8,)).astype(np.int32),
                   max_new_tokens=12)
        for _ in range(2)
    ]
    for s in streams:
        # 8 prompt + 12 new = 20 cached positions → past the 16 tile
        assert len(s.result(timeout=120.0)) == 12
    assert (eng._b_tile, eng._c_tile) == (2, 32)  # grew to cover both
    eng.drain()


# ------------------------------------------------ streaming serve protocol


def test_generate_streams_through_protocol(f32):
    from distribuuuu_tpu.lm import service as lm_service
    from distribuuuu_tpu.serve import protocol

    model = _tiny_gpt(seq_len=32)
    params = _params(model)
    eng = _engine(model, params).start()
    listener = protocol.open_listener("127.0.0.1", 0)
    port = listener.getsockname()[1]
    stop = threading.Event()
    t = threading.Thread(
        target=protocol.serve_forever,
        args=(eng, listener, stop.is_set), daemon=True,
    )
    t.start()
    try:
        frames = list(lm_service.generate_request(
            "127.0.0.1", port, tokens=[1, 2, 3], max_new_tokens=4,
        ))
        toks = [f["token"] for f in frames if f.get("stream") == "token"]
        done = frames[-1]
        assert done["stream"] == "done"
        assert done["tokens"] == toks and len(toks) >= 1
        assert done["reason"] in ("eos", "max_new_tokens", "cache_full")
        # stats ctrl frame speaks the fleet pool's warm-gate contract
        import socket

        with socket.create_connection(("127.0.0.1", port)) as c:
            protocol.send_frame(c, protocol.ctrl_request("stats"))
            st = json.loads(protocol.recv_frame(c))
        assert st["n_compiles"] >= len(st["buckets"])
        assert "jit_compiles" in st
        # oversized prompt → clean error frame, connection stays usable
        with pytest.raises(RuntimeError, match="PROMPT_LEN"):
            list(lm_service.generate_request(
                "127.0.0.1", port, tokens=list(range(99)),
            ))
    finally:
        stop.set()
        t.join(5)
        eng.drain()


def test_router_streams_generate_frames(f32):
    """The fleet router relays a generate frame sequence verbatim from a
    (fake, in-process) replica to the client — the new streaming ctrl
    frame rides the existing fleet protocol."""
    import socket

    from distribuuuu_tpu.lm import service as lm_service
    from distribuuuu_tpu.serve import protocol
    from distribuuuu_tpu.serve.fleet.router import Router

    # fake replica: answers one generate request with 3 token frames + done
    rep_listener = protocol.open_listener("127.0.0.1", 0)
    rep_port = rep_listener.getsockname()[1]

    def fake_replica():
        conn, _ = rep_listener.accept()
        with conn:
            payload = protocol.recv_frame(conn)
            ctrl = protocol.parse_ctrl(payload)
            assert ctrl["op"] == "generate"
            for i, tok in enumerate([7, 8, 9]):
                protocol.send_frame(conn, json.dumps(
                    {"stream": "token", "token": tok, "i": i}
                ).encode())
            protocol.send_frame(conn, json.dumps({
                "stream": "done", "tokens": [7, 8, 9], "n": 3,
                "reason": "max_new_tokens",
            }).encode())

    rt = threading.Thread(target=fake_replica, daemon=True)
    rt.start()
    router = Router(request_timeout_s=10.0)
    rep = router.add_replica("127.0.0.1", rep_port)
    router.mark_routable(rep.id)
    client_listener = protocol.open_listener("127.0.0.1", 0)
    client_port = client_listener.getsockname()[1]
    stop = threading.Event()
    st = threading.Thread(
        target=router.serve, args=(client_listener, stop.is_set),
        daemon=True,
    )
    st.start()
    try:
        frames = list(lm_service.generate_request(
            "127.0.0.1", client_port, tokens=[1], max_new_tokens=3,
        ))
        assert [f.get("token") for f in frames[:-1]] == [7, 8, 9]
        assert frames[-1]["stream"] == "done"
        assert int(router.registry.counter("fleet.streams").value) == 1
    finally:
        stop.set()
        st.join(5)
        rep_listener.close()


def test_router_stream_no_routable(f32):
    from distribuuuu_tpu.serve import protocol
    from distribuuuu_tpu.serve.fleet.router import Router

    import socket

    router = Router()
    listener = protocol.open_listener("127.0.0.1", 0)
    port = listener.getsockname()[1]
    stop = threading.Event()
    t = threading.Thread(
        target=router.serve, args=(listener, stop.is_set), daemon=True
    )
    t.start()
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=10) as c:
            protocol.send_frame(
                c, protocol.ctrl_request("generate", tokens=[1, 2])
            )
            resp = json.loads(protocol.recv_frame(c))
        assert resp["error"] == "no_routable_replicas"
        assert "retry_after_ms" in resp
    finally:
        stop.set()
        t.join(5)


# --------------------------------------------------- telemetry satellites


def test_generation_telemetry_and_run_report(f32, tmp_path):
    """gen.*/lm.tokens records land schema-valid in the per-rank sink;
    run_report's lm section surfaces tokens/s + decode p50/p99; the
    decode tiles carry a MEMORY-bound roofline verdict (the acceptance
    criterion the future-kernel work targets)."""
    import sys

    from distribuuuu_tpu import telemetry
    from distribuuuu_tpu.telemetry import schema

    cfg.OUT_DIR = str(tmp_path)
    telemetry.setup_from_cfg(cfg, rank=0)
    try:
        model = _tiny_gpt(seq_len=32)
        params = _params(model)
        eng = _engine(model, params, emit_interval_s=0.0).start()
        rng = np.random.default_rng(6)
        for i in range(3):
            eng.submit(
                rng.integers(0, 256, (3 + i,)).astype(np.int32),
                max_new_tokens=3,
            ).result(timeout=120.0)
        eng.drain()
    finally:
        from distribuuuu_tpu.telemetry import spans

        spans.close_telemetry()
    recs = []
    for p in glob.glob(str(tmp_path / "telemetry" / "rank*.jsonl")):
        with open(p) as f:
            recs.extend(json.loads(line) for line in f)
    kinds = {r.get("kind") for r in recs}
    assert {"gen.admit", "gen.prefill", "gen.decode", "gen.retire",
            "lm.tokens"} <= kinds
    for r in recs:
        schema.validate_record(r)
    roof = {
        r["label"]: r["bound"] for r in recs
        if r.get("kind") == "cost.roofline"
        and r["label"].startswith("gen_decode")
    }
    assert roof and all(b == "memory" for b in roof.values())
    # run_report lm section
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    sys.path.insert(0, tools)
    try:
        import run_report

        rep = run_report.build_report(str(tmp_path))
    finally:
        sys.path.remove(tools)
    lm = rep["lm"]
    assert lm["retires"] == 3 and lm["admits"] == 3
    assert lm["tokens_per_s"] is not None and lm["new_tokens"] == 9
    assert lm["decode"]["count"] > 0 and lm["decode"]["p99_ms"] > 0


def test_bench_index_has_lm_series():
    """BENCH_r08.json is committed and indexed with series names that
    cannot clobber the img/s throughput reference (the PR 8 lesson)."""
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tools = os.path.join(repo, "tools")
    sys.path.insert(0, tools)
    try:
        import bench_history
        import run_report

        index = bench_history.build_index(repo)
    finally:
        sys.path.remove(tools)
    assert "lm_train_tokens_per_s" in index["series"]
    assert "lm_generate_tokens_per_s" in index["series"]
    assert any(k.startswith("lm_decode_step_ms_") for k in index["series"])
    # regeneration pin: the committed index matches a fresh build
    with open(os.path.join(repo, "BENCH_INDEX.json")) as f:
        committed = json.load(f)
    assert committed["series"] == index["series"], (
        "BENCH_INDEX.json is stale — rerun tools/bench_history.py"
    )
    # the lm series must NOT land on the throughput gate's reference
    gated = run_report.comparable_metrics(index)
    ref = next(
        p["value"] for name, pts in index["series"].items()
        if "images_per_sec" in name and not name.endswith(
            ("_mfu", "_vs_baseline"))
        for p in pts[-1:]
    )
    assert gated["img_per_sec"] == ref  # still the resnet50 reference


@pytest.mark.slow
def test_lm_fleet_streams_with_zero_drops(tmp_path):
    """ISSUE 12 acceptance, end to end: REAL gpt replicas behind the REAL
    fleet router; concurrent clients with ragged budgets all stream to
    completion (zero dropped requests), every stream's token frames match
    its done frame, and every client of the same request gets the same
    tokens no matter which replica served it — greedy requests via
    deterministic decode, SAMPLED requests via the ctrl-frame key replay
    contract (ISSUE 17 acceptance: same temperature/top_p/seed ⇒
    bit-identical streams across real replicas)."""
    import socket

    from distribuuuu_tpu.lm import service as lm_service
    from distribuuuu_tpu.serve import protocol
    from distribuuuu_tpu.serve.fleet import FleetService

    config.reset_cfg()
    cfg.MODEL.ARCH = "gpt_nano"
    cfg.MODEL.NUM_CLASSES = 320
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.DEVICE.PLATFORM = "cpu"
    cfg.LM.SEQ_LEN = 32
    cfg.GENERATE.PROMPT_LEN = 8
    cfg.GENERATE.MAX_NEW_TOKENS = 6
    cfg.GENERATE.BATCH_TILES = [2]
    cfg.GENERATE.CACHE_TILES = [16]
    cfg.RNG_SEED = 0
    cfg.OUT_DIR = str(tmp_path)
    cfg.SERVE.FLEET.AUTOSCALE = False
    cfg.SERVE.FLEET.HEALTH_PERIOD_S = 0.5
    cfg_path = os.path.join(str(tmp_path), "fleet_cfg.yaml")
    with open(cfg_path, "w") as f:
        f.write(cfg.dump())

    svc = FleetService(cfg, 2, cfg_path=cfg_path, out_dir=str(tmp_path))
    try:
        svc.start(wait=True)
        assert svc.router.n_routable() == 2, (
            f"replicas failed warm-up; see fleet/replica*.log in {tmp_path}"
        )
        listener = protocol.open_listener("127.0.0.1", 0)
        port = listener.getsockname()[1]
        stop = threading.Event()
        server = threading.Thread(
            target=svc.serve, args=(listener, stop.is_set),
            kwargs=dict(poll_s=0.05), daemon=True,
        )
        server.start()
        rng = np.random.default_rng(12)
        # 5 request groups x 2 identical clients: groups 0-1 greedy,
        # groups 2-4 sampled with a per-group ctrl-frame key — the pair
        # may land on different replicas and must still match
        gprompts = [
            rng.integers(0, 256, (2 + g,)).astype(int).tolist()
            for g in range(5)
        ]
        results: dict[int, dict] = {}
        errors: list = []

        def client(i):
            g = i % 5
            kw = {} if g < 2 else dict(
                temperature=0.9, top_p=0.9, seed=50 + g,
            )
            try:
                frames = list(lm_service.generate_request(
                    "127.0.0.1", port, tokens=gprompts[g],
                    max_new_tokens=3 + g, timeout=120.0, **kw,
                ))
                toks = [
                    f["token"] for f in frames if f.get("stream") == "token"
                ]
                results[i] = {"frames": frames, "tokens": toks}
            except Exception as e:  # noqa: BLE001
                errors.append((i, e))

        n_clients = 10
        threads = [
            threading.Thread(target=client, args=(i,))
            for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(180.0)
        stop.set()
        server.join(5)
        assert not errors, errors
        assert len(results) == n_clients  # zero dropped requests
        by_group: dict[int, list] = {}
        for i, r in results.items():
            done = r["frames"][-1]
            assert done["stream"] == "done" and "error" not in done
            assert done["tokens"] == r["tokens"]
            assert len(r["tokens"]) >= 1
            by_group.setdefault(i % 5, []).append(tuple(r["tokens"]))
        for g, outs in by_group.items():
            # determinism across replicas: an identical request — greedy
            # (g < 2) or sampled with the same ctrl-frame key (g >= 2) —
            # streams the same tokens, whichever replica decoded it
            assert len(outs) == 2 and len(set(outs)) == 1, (g, outs)
        assert int(svc.router.registry.counter("fleet.streams").value) \
            == n_clients
    finally:
        svc.shutdown()


def test_tokenizer_roundtrip_and_identity():
    from distribuuuu_tpu.lm.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    ids = tok.encode("hello, wörld")
    assert ids.dtype == np.uint16 and int(ids.max()) < 256
    assert tok.decode(ids) == "hello, wörld"
    assert tok.decode(list(ids) + [tok.eos_id, 300]) == "hello, wörld"
    ident = tok.identity()
    assert ident == {
        "tokenizer": "byte-v1", "vocab_size": 320, "eos_id": 256,
    }
    assert tok.vocab_size % 64 == 0  # even TP sharding of the vocab dim
