"""Space-to-depth stem (DEVICE.S2D_STEM / models.layers.StemConv7x7): the
folded 4x4/s1 compute path must be an exact reformulation of the 7x7/s2 stem
— same params at the same tree paths, same outputs, odd-size fallback."""

import jax
import jax.numpy as jnp
import numpy as np

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
import pytest


def _stem_pair():
    from distribuuuu_tpu.models.layers import StemConv7x7

    return (
        StemConv7x7(64, s2d=False, dtype=jnp.float32),
        StemConv7x7(64, s2d=True, dtype=jnp.float32),
    )


def test_s2d_stem_matches_plain_conv():
    plain, s2d = _stem_pair()
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 224, 224, 3)), jnp.float32
    )
    variables = plain.init(jax.random.key(0), x)
    ref = plain.apply(variables, x)
    out = s2d.apply(variables, x)  # SAME variables — the param is shared
    assert out.shape == ref.shape == (2, 112, 112, 64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)


def test_s2d_stem_param_tree_identical():
    plain, s2d = _stem_pair()
    x = jnp.ones((1, 32, 32, 3), jnp.float32)
    va = jax.tree.map(np.shape, jax.eval_shape(plain.init, jax.random.key(0), x))
    vb = jax.tree.map(np.shape, jax.eval_shape(s2d.init, jax.random.key(0), x))
    assert jax.tree.structure(va) == jax.tree.structure(vb)
    # same SHAPES too: the s2d mode must keep the canonical (7,7,in,out)
    # kernel, not a folded one (leaves flatten through the Partitioned box
    # to the shape-tuple elements)
    assert jax.tree.leaves(va) == jax.tree.leaves(vb) == [7, 7, 3, 64]


def test_s2d_stem_gradients_match():
    plain, s2d = _stem_pair()
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((1, 64, 64, 3)), jnp.float32
    )
    variables = plain.init(jax.random.key(0), x)

    def loss(v, mod):
        return jnp.sum(mod.apply(v, x) ** 2)

    ga = jax.grad(loss)(variables, plain)
    gb = jax.grad(loss)(variables, s2d)
    for a, b in zip(jax.tree.leaves(ga), jax.tree.leaves(gb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-2)


def test_s2d_stem_odd_input_falls_back():
    _, s2d = _stem_pair()
    x = jnp.ones((1, 225, 225, 3), jnp.float32)
    variables = s2d.init(jax.random.key(0), x)
    out = s2d.apply(variables, x)
    # torch conv output size: floor((225 + 6 - 7)/2) + 1 = 113
    assert out.shape == (1, 113, 113, 64)


def test_resnet_checkpoint_compatible_across_modes():
    """A model initialized with the plain stem evaluates identically under
    the s2d stem — the checkpoint-compatibility guarantee."""
    from distribuuuu_tpu import models

    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((2, 64, 64, 3)), jnp.float32
    )
    plain = models.build_model(
        "resnet18", num_classes=10, dtype=jnp.float32, s2d_stem=False
    )
    folded = models.build_model(
        "resnet18", num_classes=10, dtype=jnp.float32, s2d_stem=True
    )
    variables = plain.init(jax.random.key(0), x, train=False)
    a = plain.apply(variables, x, train=False)
    b = folded.apply(variables, x, train=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4)


@pytest.mark.slow  # dominates the fast tier; full tier covers it
def test_densenet_checkpoint_compatible_across_modes():
    from distribuuuu_tpu import models

    x = jnp.asarray(
        np.random.default_rng(3).standard_normal((1, 64, 64, 3)), jnp.float32
    )
    plain = models.build_model(
        "densenet121", num_classes=10, dtype=jnp.float32, s2d_stem=False
    )
    folded = models.build_model(
        "densenet121", num_classes=10, dtype=jnp.float32, s2d_stem=True
    )
    variables = plain.init(jax.random.key(0), x, train=False)
    a = plain.apply(variables, x, train=False)
    b = folded.apply(variables, x, train=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4)


def test_trainer_wires_s2d_from_cfg():
    from distribuuuu_tpu import trainer

    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.DEVICE.S2D_STEM = True
    assert trainer.build_model_from_cfg().s2d_stem is True
    cfg.DEVICE.S2D_STEM = False
    assert trainer.build_model_from_cfg().s2d_stem is False
    # archs without a 7x7 stem must not receive the kwarg
    cfg.MODEL.ARCH = "efficientnet_b0"
    trainer.build_model_from_cfg()
