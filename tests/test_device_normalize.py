"""DATA.DEVICE_NORMALIZE: ship uint8, normalize in-graph.

Motivated by a direct r3 measurement (PERF.md "Real-JPEG"): this
environment's host→device path moves ~3.5 MB/s raw, so a float32 batch is
4× the bytes of the information it carries — pixels are uint8 after
PIL/native resampling either way. These tests pin the equivalence: the
uint8 pipeline + in-graph normalize produces the SAME tensors as the
host-normalized float pipeline, end to end.
"""

import numpy as np
import pytest
from PIL import Image

import jax.numpy as jnp

from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.data.loader import Loader, construct_train_loader
from distribuuuu_tpu.data.imagefolder import ImageFolderDataset
from distribuuuu_tpu.data.transforms import (
    normalize_in_graph,
    to_normalized_array,
    to_u8_array,
)


def _tree(root, n_per_class=3):
    rng = np.random.default_rng(0)
    for cls in ("a", "b"):
        d = root / "train" / cls
        d.mkdir(parents=True)
        for i in range(n_per_class):
            w, h = int(rng.integers(50, 90)), int(rng.integers(50, 90))
            arr = rng.integers(0, 256, size=(h, w, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{i}.jpg", "JPEG", quality=92)
    import shutil

    shutil.copytree(root / "train", root / "val")
    return str(root)


def test_normalize_in_graph_matches_host_normalize():
    rng = np.random.default_rng(1)
    u8 = rng.integers(0, 256, size=(2, 8, 8, 3), dtype=np.uint8)
    img0 = Image.fromarray(u8[0])
    host = to_normalized_array(img0)
    dev = np.asarray(normalize_in_graph(jnp.asarray(u8)))[0]
    np.testing.assert_allclose(dev, host, atol=1e-6)
    assert np.array_equal(to_u8_array(img0), u8[0])


@pytest.mark.parametrize("train", [True, False])
def test_u8_dataset_plus_device_normalize_equals_float_dataset(
    tmp_path, train
):
    """Same files, same augmentation stream: uint8 pipeline + in-graph
    normalize == host-normalized float pipeline (both splits)."""
    root = _tree(tmp_path)
    kw = dict(
        root=root, split="train" if train else "val",
        im_size=32 if train else 48, train=train, base_seed=7,
        crop_size=None if train else 32, backend="pil",
    )
    ds_f = ImageFolderDataset(**kw)
    ds_u = ImageFolderDataset(**kw, raw_u8=True)
    ds_f.set_epoch_seed(2)
    ds_u.set_epoch_seed(2)
    idxs = np.arange(len(ds_f))
    imgs_f, labels_f = ds_f.load_batch(idxs)
    imgs_u, labels_u = ds_u.load_batch(idxs)
    assert imgs_u.dtype == np.uint8
    np.testing.assert_array_equal(labels_f, labels_u)
    np.testing.assert_allclose(
        np.asarray(normalize_in_graph(jnp.asarray(imgs_u))),
        imgs_f, atol=1e-6,
    )


def test_loader_ships_uint8_batches_with_uint8_padding():
    cfg.MODEL.DUMMY_INPUT = True
    cfg.DATA.DEVICE_NORMALIZE = True
    cfg.TRAIN.BATCH_SIZE = 2
    cfg.TRAIN.IM_SIZE = 16
    loader = construct_train_loader()
    batch = next(iter(loader))
    assert batch["image"].dtype == np.uint8
    # ragged-tail padding path keeps the dtype
    ds = loader.dataset
    small = Loader(ds, batch_size=len(ds) + 8, shuffle=False,
                   drop_last=False, workers=1)
    padded = next(iter(small))
    assert padded["image"].dtype == np.uint8
    assert padded["mask"].sum() < len(padded["mask"])


def test_native_u8_matches_pil_u8(tmp_path):
    """The C++ raw-u8 kernel agrees with the PIL uint8 path within the
    resampler quantization bound (≤3 counts — same bound the normalized
    parity test uses)."""
    from distribuuuu_tpu import native

    if not native.available():
        pytest.skip(f"native kernel unavailable: {native.build_error()}")
    root = _tree(tmp_path, n_per_class=4)
    kw = dict(root=root, split="train", im_size=32, train=True,
              base_seed=5, raw_u8=True)
    ds_nat = ImageFolderDataset(**kw, backend="native")
    ds_pil = ImageFolderDataset(**kw, backend="pil")
    idxs = np.arange(len(ds_nat))
    imgs_n, _ = ds_nat.load_batch(idxs)
    imgs_p, _ = ds_pil.load_batch(idxs)
    assert imgs_n.dtype == imgs_p.dtype == np.uint8
    diff = np.abs(imgs_n.astype(np.int16) - imgs_p.astype(np.int16))
    assert diff.max() <= 3, diff.max()
