"""Pallas flash attention (ops/flash_attention.py) — VERDICT r1 item 4.

Correctness on the CPU mesh runs the kernels through the Pallas interpreter
(``interpret=True``) against the dense reference — forward AND both backward
kernels (dq, dk/dv), including the padded (L not a block multiple) case
whose masked rows/keys are the easy thing to get wrong.

The performance claim (≥1.2× over the lax.scan blockwise path at
[4, 3, 4096, 64] on a v5e — measured 1.23× fwd+bwd with the DCE-safe
harness, tools/flash_bench.py / PERF.md) is hardware-gated and not
asserted here.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distribuuuu_tpu.ops import flash_attention as fa
from distribuuuu_tpu.ops.ring_attention import reference_attention

BLK = dict(blk_q=256, blk_k=256)


@pytest.mark.parametrize(
    "B,H,L,D",
    [
        (2, 3, 512, 64),   # block multiple
        (1, 2, 300, 64),   # padded L (masked keys + padded q rows)
        (2, 2, 640, 32),   # L > blk, not a multiple; small head dim
    ],
)
def test_forward_matches_reference(B, H, L, D):
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((B, H, L, D)), jnp.float32)
        for _ in range(3)
    )
    out = fa.flash_attention(q, k, v, interpret=True, **BLK)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("L", [512, 300])
def test_gradients_match_reference(L):
    rng = np.random.default_rng(1)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 2, L, 64)), jnp.float32)
        for _ in range(3)
    )
    w = jnp.asarray(rng.standard_normal((64,)), jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) * w)

    gf = jax.grad(
        loss(lambda q, k, v: fa.flash_attention(q, k, v, interpret=True, **BLK)),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(loss(reference_attention), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, err_msg=name
        )


def test_cpu_fallback_is_blockwise():
    """Off-TPU the public entry point must run (and agree) without Pallas."""
    rng = np.random.default_rng(2)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 2, 256, 32)), jnp.float32)
        for _ in range(3)
    )
    out = fa.flash_attention(q, k, v)  # backend is cpu in tests → fallback
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize(
    "B,H,L,D",
    [
        (2, 2, 512, 64),   # block multiple: exercises the block-skip bounds
        (1, 2, 300, 32),   # padded L: causal ∧ pad masks compose
    ],
)
def test_causal_forward_matches_reference(B, H, L, D):
    """Causal in-kernel (r4): fully-masked K blocks are skipped by loop
    bound, diagonal blocks masked elementwise — must equal dense causal."""
    rng = np.random.default_rng(4)
    q, k, v = (
        jnp.asarray(rng.standard_normal((B, H, L, D)), jnp.float32)
        for _ in range(3)
    )
    out = fa.flash_attention(q, k, v, causal=True, interpret=True, **BLK)
    ref = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("L", [512, 300])
def test_causal_gradients_match_reference(L):
    """All three causal backward paths (dq block-skip, dk/dv start-offset,
    diagonal masks) against the dense causal reference."""
    rng = np.random.default_rng(5)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 2, L, 64)), jnp.float32)
        for _ in range(3)
    )
    w = jnp.asarray(rng.standard_normal((64,)), jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) * w)

    gf = jax.grad(
        loss(lambda q, k, v: fa.flash_attention(
            q, k, v, causal=True, interpret=True, **BLK
        )),
        argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        loss(lambda q, k, v: reference_attention(q, k, v, causal=True)),
        argnums=(0, 1, 2),
    )(q, k, v)
    for a, b, name in zip(gf, gr, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, err_msg=name
        )


def test_causal_matches_blockwise_scan():
    """The causal kernel against the scan path it previously fell back to
    (the VERDICT r3 #4 'exactness test vs the causal blockwise path')."""
    from distribuuuu_tpu.ops.ring_attention import blockwise_attention

    rng = np.random.default_rng(6)
    q, k, v = (
        jnp.asarray(rng.standard_normal((1, 3, 384, 64)), jnp.float32)
        for _ in range(3)
    )
    out = fa.flash_attention(q, k, v, causal=True, interpret=True, **BLK)
    ref = blockwise_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_with_lse_matches_and_differentiates():
    """flash_attention_with_lse: the lse output equals the dense
    log-sum-exp, and a loss that consumes BOTH outputs gets exact
    gradients (the lse cotangent folds into the kernels' delta — the
    property ring attention's flash block updates rely on)."""
    rng = np.random.default_rng(7)
    B, H, L, D = 1, 2, 256, 32
    q, k, v = (
        jnp.asarray(rng.standard_normal((B, H, L, D)), jnp.float32)
        for _ in range(3)
    )
    scale = D ** -0.5

    o, lse = fa.flash_attention_with_lse(q, k, v, interpret=True, **BLK)
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    np.testing.assert_allclose(
        np.asarray(lse), np.asarray(jax.nn.logsumexp(s, axis=-1)),
        atol=2e-5,
    )
    np.testing.assert_allclose(
        np.asarray(o), np.asarray(reference_attention(q, k, v)), atol=2e-5
    )

    wo = jnp.asarray(rng.standard_normal((D,)), jnp.float32)

    def loss_flash(q, k, v):
        o, lse = fa.flash_attention_with_lse(
            q, k, v, interpret=True, **BLK
        )
        return jnp.sum(o * wo) + jnp.sum(jnp.sin(lse))

    def loss_ref(q, k, v):
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
        ) * scale
        o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
        return jnp.sum(o * wo) + jnp.sum(jnp.sin(jax.nn.logsumexp(s, -1)))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=5e-5, err_msg=name
        )


def test_auto_resolution_threshold():
    """The 'auto' branch itself: flash at ≥1024 tokens with dropout 0,
    dense below / with dropout; explicit impls pass through."""
    from distribuuuu_tpu.models.vit import Attention

    assert Attention.resolve_impl("auto", 1024, 0.0) == "flash"
    assert Attention.resolve_impl("auto", 4096, 0.0) == "flash"
    assert Attention.resolve_impl("auto", 1023, 0.0) == "xla"
    assert Attention.resolve_impl("auto", 4096, 0.1) == "xla"  # no p-dropout
    assert Attention.resolve_impl("xla", 4096, 0.0) == "xla"
    assert Attention.resolve_impl("blockwise", 64, 0.0) == "blockwise"


@pytest.mark.slow
def test_vit_auto_resolves_by_length():
    """Through the real model: a ≥1024-token input drives the auto→flash
    branch (CPU fallback executes the blockwise math), a 64-token input
    the auto→xla branch; both produce finite logits."""
    from distribuuuu_tpu import models

    rng = np.random.default_rng(3)
    cases = [
        (128, 16, "auto"),   # 64 tokens  → xla
        (256, 8, "auto"),    # 1024 tokens → flash (threshold branch)
        (128, 16, "flash"),  # forced flash, short seq
    ]
    for size, patch, impl in cases:
        m = models.build_model(
            "vit_tiny", num_classes=10, dtype=jnp.float32, patch=patch,
            depth=1, dim=32, num_heads=2, attn_impl=impl,
        )
        x = jnp.asarray(
            rng.standard_normal((1, size, size, 3)), jnp.float32
        )
        vs = m.init(jax.random.key(0), x, train=False)
        logits = m.apply(vs, x, train=False)
        assert np.isfinite(np.asarray(logits)).all(), (size, patch, impl)


def test_trainer_accepts_flash_impl():
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.config import cfg

    cfg.MODEL.ARCH = "vit_tiny"
    cfg.DEVICE.ATTN_IMPL = "flash"
    model = trainer.build_model_from_cfg()
    assert model.attn_impl == "flash"
    cfg.DEVICE.ATTN_IMPL = "auto"
    model = trainer.build_model_from_cfg()
    assert model.attn_impl == "auto"
