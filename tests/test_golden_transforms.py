"""Golden-oracle test for the data transforms — VERDICT r1 item 7.

`tests/data/golden_transforms.npz` pins, for seed-pinned structured images:
the val pipeline output (Resize shorter-side + CenterCrop + Normalize,
torchvision semantics, ref: /root/reference/distribuuuu/utils.py:163-172),
the train pipeline output (RandomResizedCrop + flip + Normalize,
ref: utils.py:127-139), and the RRC box/flip/geom streams.

What this protects: a refactor of the transform geometry that still keeps
PIL-path == native-path (the equality the unit tests check) would slip
through silently; against the checked-in goldens any numerics drift fails.
Source images are regenerated from seeds as raw arrays (no codec in the
loop — PIL↔native codec agreement is tests/test_native_decode.py's job).

The repo-owned streams (RRC boxes, flips, geoms) must match EXACTLY —
they are pure Python/numpy. The resampled pixel outputs go through
Pillow's C bilinear resampler, so they get a ±2-count tolerance (a
Pillow upgrade may legally shift rounding by one uint8 count); the
native C++ path matches within its documented quantization bound
(native/decode.cc).
"""

import os

import numpy as np
import pytest
from PIL import Image

from distribuuuu_tpu.data import transforms as T

GOLDEN = os.path.join(os.path.dirname(__file__), "data", "golden_transforms.npz")


@pytest.fixture(scope="module")
def golden():
    return np.load(GOLDEN)


def _cases(golden):
    for idx in range(len(golden["sizes"])):
        yield idx, golden[f"src_{idx}"]


# ±2 uint8 counts in normalized space: 2/255 / min(std) ≈ 0.035
RESAMPLE_ATOL = 0.035


def test_val_pipeline_matches_golden(golden):
    for idx, src in _cases(golden):
        img = Image.fromarray(src)
        got = T.val_transform(img, 48, 32)
        np.testing.assert_allclose(
            got, golden[f"val_{idx}"], atol=RESAMPLE_ATOL,
            err_msg=f"val case {idx}",
        )


def test_train_pipeline_matches_golden(golden):
    for idx, src in _cases(golden):
        img = Image.fromarray(src)
        rng = np.random.default_rng(1000 + idx)
        got = T.train_transform(img, 32, rng)
        np.testing.assert_allclose(
            got, golden[f"train_{idx}"], atol=RESAMPLE_ATOL,
            err_msg=f"train case {idx}",
        )


def test_rrc_box_and_flip_stream_matches_golden(golden):
    """The exact torchvision box-sampling draw sequence: 10-attempt
    area/ratio jitter + center fallback, then the flip draw — any change
    to draw order or arithmetic shifts every augmentation downstream."""
    sizes = [tuple(s) for s in golden["sizes"]]
    rng = np.random.default_rng(42)
    boxes, flips = [], []
    for (w, h) in sizes * 4:
        boxes.append(T.sample_rrc_box(w, h, rng))
        flips.append(1 if rng.random() < 0.5 else 0)
    np.testing.assert_array_equal(np.asarray(boxes, np.int64), golden["boxes"])
    np.testing.assert_array_equal(np.asarray(flips, np.int64), golden["flips"])


def test_train_geom_stream_matches_golden(golden):
    """train_geom (the native backend's geometry) must consume the SAME rng
    stream as the PIL path — pinned as float64 exactly."""
    sizes = [tuple(s) for s in golden["sizes"]]
    rng = np.random.default_rng(42)
    geoms = [T.train_geom(w, h, 32, rng) for (w, h) in sizes * 4]
    np.testing.assert_array_equal(
        np.asarray(geoms, np.float64), golden["geoms"]
    )


def test_native_val_path_matches_golden_within_quantization(tmp_path, golden):
    """The C++ backend's val output vs the goldens (PNG round-trip is
    lossless, so only the resampler differs — bounded by its documented
    ±few-counts uint8 quantization, ~3/255/min(std) in normalized space)."""
    from distribuuuu_tpu import native

    if not native.available():
        pytest.skip(f"native kernel unavailable: {native.build_error()}")
    for idx, src in _cases(golden):
        p = str(tmp_path / f"g{idx}.png")
        Image.fromarray(src).save(p, "PNG")
        h, w = src.shape[:2]
        geom = np.asarray(
            [T.val_geom(w, h, 48, 32) + (0,)],  # trailing struct padding
            dtype=native.GEOM_DTYPE,
        )
        imgs, status = native.load_batch(
            [p], geom, (32, 32), T.IMAGENET_MEAN, T.IMAGENET_STD, 1
        )
        assert status[0] == 0
        np.testing.assert_allclose(
            imgs[0], golden[f"val_{idx}"], atol=0.06,
            err_msg=f"native val case {idx}",
        )
