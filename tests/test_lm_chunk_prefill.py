"""Chunked paged prefill (ISSUE 19, the long-context serving tentpole):
a prompt streams into its KV page in fixed CHUNK_PREFILL-token
prefill-shaped calls — pinned logit-identical (float tol) to
whole-prompt prefill, greedy-token-identical on the continuation
(including composed with speculative decoding), admitting prompts past
GENERATE.PROMPT_LEN with zero steady-state recompiles, and refusing
mis-sized chunks with the arithmetic in-message."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.lm import generate as G


def _tiny_gpt(seq_len=32, vocab=320, dtype=jnp.float32, **kw):
    from distribuuuu_tpu.models.gpt import GPT

    return GPT(
        vocab_size=vocab, seq_len=seq_len, dim=32, depth=2, num_heads=2,
        dtype=dtype, **kw,
    )


def _params(model, key=0):
    return model.init(
        jax.random.key(key), model.dummy_input(), train=False
    )["params"]


def _engine(model, params, **kw):
    kw.setdefault("prompt_len", 8)
    kw.setdefault("max_new_tokens", 6)
    kw.setdefault("batch_tiles", [2])
    kw.setdefault("cache_tiles", [16])
    return G.GenerateEngine(model, {"params": params}, **kw)


@pytest.fixture()
def f32(monkeypatch):
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    yield


def test_chunk_page_logits_match_whole_prompt_prefill(f32):
    """THE pin: building a page chunk by chunk yields the same per-
    position logits (float tol) as the one whole-prompt prefill call —
    the chunk math IS the prefill math, re-windowed."""
    model = _tiny_gpt(seq_len=32)
    params = _params(model)
    whole = _engine(model, params, cache_tiles=[32], prompt_len=8)
    chunked = _engine(model, params, cache_tiles=[32], prompt_len=8,
                      chunk_prefill=4)
    rng = np.random.default_rng(11)
    prompt = rng.integers(0, 256, (6,)).astype(np.int32)
    # whole-prompt reference logits over the prompt positions
    padded = np.zeros((1, 8), np.int32)
    padded[0, :6] = prompt
    ref, _ = whole._prefill_exec[8](whole._variables, jnp.asarray(padded))
    ref = np.asarray(ref)[0, :6]
    # chunk stream: 2 calls of width 4 into a 32-wide page
    W, plen = 4, 6
    page = chunked._zero_cache(1, 32)
    rows = []
    for k in range(-(-plen // W)):
        seg = prompt[k * W:(k + 1) * W]
        chunk = np.zeros((1, W), np.int32)
        chunk[0, :len(seg)] = seg
        logits, page = chunked._chunk_exec[32](
            chunked._variables, jnp.asarray(chunk),
            jnp.full((1,), k * W, jnp.int32), page,
        )
        rows.append(np.asarray(logits)[0])
    got = np.concatenate(rows)[:plen]
    np.testing.assert_allclose(got, ref, atol=1e-4)
    whole.drain()
    chunked.drain()


def test_chunked_stream_greedy_identical_and_no_recompiles(f32):
    """Ragged prompt lengths through the chunked engine produce EXACTLY
    the whole-prompt engine's greedy streams, and n_compiles stays at
    its startup value — steady state never recompiles."""
    model = _tiny_gpt(seq_len=32)
    params = _params(model)
    whole = _engine(model, params, batch_tiles=[1, 2], cache_tiles=[16, 32],
                    prompt_len=8, max_new_tokens=6).start()
    chunked = _engine(model, params, batch_tiles=[1, 2],
                      cache_tiles=[16, 32], prompt_len=8, max_new_tokens=6,
                      chunk_prefill=4).start()
    n0 = chunked.n_compiles
    rng = np.random.default_rng(12)
    prompts = [
        rng.integers(0, 256, (n,)).astype(np.int32)
        for n in (1, 3, 4, 5, 7, 8)  # ragged, multiple, sub-chunk
    ]
    for p in prompts:
        a = whole.submit(p).result(timeout=120.0)
        b = chunked.submit(p).result(timeout=120.0)
        assert a == b, (len(p), a, b)
    st = chunked.stats()
    assert chunked.n_compiles == n0
    assert st["chunk_prefill"] == 4
    assert st["chunk_prefills"] == len(prompts)
    whole.drain()
    chunked.drain()


def test_chunked_prefill_admits_past_prompt_len(f32):
    """The point of chunking: a prompt longer than GENERATE.PROMPT_LEN —
    which the whole-prompt engine refuses — admits through the chunk
    stream and continues greedy-identical to the teacher-forced
    reference."""
    model = _tiny_gpt(seq_len=32)
    params = _params(model)
    whole = _engine(model, params, cache_tiles=[32], prompt_len=8,
                    max_new_tokens=4)
    rng = np.random.default_rng(13)
    long_prompt = rng.integers(0, 256, (20,)).astype(np.int32)
    with pytest.raises(ValueError, match="exceeds\\s+GENERATE.PROMPT_LEN=8"):
        whole.submit(long_prompt)
    whole.drain()
    chunked = _engine(model, params, cache_tiles=[32], prompt_len=8,
                      max_new_tokens=4, chunk_prefill=8).start()
    out = chunked.submit(long_prompt, max_new_tokens=4).result(timeout=120.0)
    assert len(out) == 4
    seq = list(long_prompt)
    for tok in out:
        lg = model.apply({"params": params},
                         jnp.asarray(np.asarray(seq)[None]), train=False)
        assert tok == int(np.asarray(lg)[0, -1].argmax())
        seq.append(tok)
    chunked.drain()


def test_chunked_prefill_composes_with_speculative_decode(f32):
    """Chunk-admitted requests speculate off a fully-mirrored draft page:
    the emitted greedy stream equals plain target-only decode, for short
    AND past-PROMPT_LEN prompts."""
    target = _tiny_gpt(seq_len=32)
    tparams = _params(target, key=0)
    draft = _tiny_gpt(seq_len=32)
    dparams = _params(draft, key=1)
    plain = _engine(target, tparams, batch_tiles=[1], cache_tiles=[32],
                    prompt_len=24, max_new_tokens=5).start()
    spec = _engine(target, tparams, batch_tiles=[1], cache_tiles=[32],
                   prompt_len=8, max_new_tokens=5, chunk_prefill=4,
                   draft_model=draft,
                   draft_variables={"params": dparams}, spec_k=2).start()
    rng = np.random.default_rng(14)
    for n in (3, 6, 11):
        p = rng.integers(0, 256, (n,)).astype(np.int32)
        assert plain.submit(p).result(timeout=120.0) == \
            spec.submit(p).result(timeout=120.0), n
    st = spec.stats()
    assert st["spec_rounds"] > 0 and st["chunk_prefills"] == 3
    plain.drain()
    spec.drain()


def test_chunk_prefill_validation_arithmetic(f32):
    """The refusal suite: every mis-configuration names its numbers."""
    model = _tiny_gpt(seq_len=64)
    params = _params(model)
    # chunk does not divide a page-capable tile — quotient in-message
    with pytest.raises(ValueError, match=r"16 % 5 = 1"):
        G.validate_chunk_prefill_cfg(5, [16, 32])
    # chunk larger than every tile
    with pytest.raises(ValueError, match="exceeds the largest"):
        G.validate_chunk_prefill_cfg(64, [16, 32])
    with pytest.raises(ValueError, match=">= 1"):
        G.validate_chunk_prefill_cfg(0, [16])
    # engine-level: the same refusal fires at build
    with pytest.raises(ValueError, match=r"24 % 16 = 8"):
        _engine(model, params, cache_tiles=[24], prompt_len=8,
                max_new_tokens=4, chunk_prefill=16)
    # submit bound carries the sum: plen + max_new > largest tile
    eng = _engine(model, params, cache_tiles=[16], prompt_len=8,
                  max_new_tokens=6, chunk_prefill=4)
    with pytest.raises(ValueError, match=r"11 \+ max_new=6 > largest"):
        eng.submit(np.arange(11, dtype=np.int32))
    eng.drain()


def test_chunk_prefill_telemetry_kind(f32, tmp_path):
    """gen.chunk_prefill records land schema-valid in the span sink
    (satellite: telemetry/schema.py declares the kind)."""
    import glob
    import json

    from distribuuuu_tpu import telemetry
    from distribuuuu_tpu.telemetry import schema

    cfg.OUT_DIR = str(tmp_path)
    telemetry.setup_from_cfg(cfg, rank=0)
    try:
        model = _tiny_gpt(seq_len=32)
        params = _params(model)
        eng = _engine(model, params, cache_tiles=[32], prompt_len=8,
                      max_new_tokens=3, chunk_prefill=4).start()
        eng.submit(np.arange(10, dtype=np.int32)).result(timeout=120.0)
        eng.drain()
    finally:
        from distribuuuu_tpu.telemetry import spans

        spans.close_telemetry()
    recs = []
    for p in glob.glob(str(tmp_path / "telemetry" / "rank*.jsonl")):
        with open(p) as f:
            recs.extend(json.loads(line) for line in f)
    chunk_recs = [r for r in recs if r.get("kind") == "gen.chunk_prefill"]
    assert len(chunk_recs) == 1
    assert chunk_recs[0]["tokens"] == 10
    assert chunk_recs[0]["chunk"] == 4 and chunk_recs[0]["chunks"] == 3
    assert not any(r.get("kind") == "gen.prefill" for r in recs)
    for r in recs:
        schema.validate_record(r)
    # run_report surfacing (satellite): the lm section carries the
    # chunked-prefill line and the per-class admission mix
    import os
    import sys

    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    sys.path.insert(0, tools)
    try:
        import run_report

        rep = run_report.build_report(str(tmp_path))
    finally:
        sys.path.remove(tools)
    lm = rep["lm"]
    assert lm["chunk_prefill"]["prompts"] == 1
    assert lm["chunk_prefill"]["chunk_calls"] == 3
    assert lm["chunk_prefill"]["p50_ms"] > 0
    assert lm["admit_length_classes"] == {"short": 1}
