"""Rendezvous derivation units + the bit-reproducibility claim.

The Slurm branch of ``setup_distributed`` must derive the coordinator from
SLURM_* env exactly as the reference does (ref: utils.py:26-40); and a fixed
RNG_SEED must make training bit-reproducible (README troubleshooting
section's promise).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer
from distribuuuu_tpu.parallel import mesh as mesh_lib, sharding as sharding_lib
from distribuuuu_tpu.utils.optim import construct_optimizer


def test_slurm_env_derivation(monkeypatch):
    monkeypatch.setenv("SLURM_PROCID", "3")
    monkeypatch.setenv("SLURM_NTASKS", "8")
    monkeypatch.setenv("SLURM_NODELIST", "tpu-[000-007]")
    # scontrol is not installed here; emulate the shell-out faithfully — the
    # production command pipes through `head -n1` (ref: utils.py:30)
    def fake_shell(cmd):
        assert "scontrol show hostname tpu-[000-007]" in cmd
        out = "tpu-000\ntpu-001\n"
        return out.splitlines()[0] if "head -n1" in cmd else out

    monkeypatch.setattr(mesh_lib.subprocess, "getoutput", fake_shell)
    addr, n_procs, proc_id = mesh_lib._slurm_env()
    assert addr == "tpu-000"
    assert n_procs == 8 and proc_id == 3


def _train_params_sum(seed):
    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.RNG_SEED = seed
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    mesh = mesh_lib.build_mesh()
    model = trainer.build_model_from_cfg()
    state = trainer.create_train_state(model, jax.random.key(seed), mesh, 32)
    step = trainer.make_train_step(model, construct_optimizer(), topk=5)
    rng = np.random.default_rng(seed)
    for _ in range(5):
        images = rng.standard_normal((8, 32, 32, 3)).astype(np.float32)
        labels = rng.integers(0, 10, (8,)).astype(np.int32)
        batch = sharding_lib.shard_batch(mesh, {
            "image": images, "label": labels,
            "mask": np.ones((8,), np.float32),
        })
        state, _ = step(state, batch)
    return [np.asarray(x) for x in jax.tree.leaves(state.params)]


@pytest.mark.slow  # dominates the fast tier; full tier covers it
def test_fixed_seed_is_bit_reproducible():
    a = _train_params_sum(7)
    b = _train_params_sum(7)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
    c = _train_params_sum(8)
    assert any(
        not np.array_equal(x, y) for x, y in zip(a, c)
    ), "different seeds produced identical params"
