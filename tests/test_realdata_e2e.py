"""Real-JPEG ImageFolder training through the actual CLI (VERDICT r2 #1).

Every other e2e test sets ``MODEL.DUMMY_INPUT True``; these drive
``python train_net.py`` / ``test_net.py`` as subprocesses over a real tree
of JPEG files (tools/make_imagefolder.py), exercising the full
decode → augment → shard → step seam: threaded prefetch against dispatch,
the native C++ decode backend under load, epoch reshuffle across workers,
auto-resume, and PIL↔native eval agreement.

Mirrors the reference's primary documented workflow (ref:
/root/reference/README.md:94-107 — ImageFolder training; loaders
/root/reference/distribuuuu/utils.py:121-152).
"""

import json
import os
import re
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow  # subprocess compiles on the 1-core CPU mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `pytest` without `python -m` lacks cwd on path
    sys.path.insert(0, REPO)

N_CLASSES = 4


def _run_cli(script, *overrides, check=True):
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, script),
            "--cfg", os.path.join(REPO, "config", "resnet18.yaml"),
            *map(str, overrides),
        ],
        capture_output=True, text=True, timeout=900, env=env, cwd=REPO,
    )
    if check and proc.returncode != 0:
        raise AssertionError(
            f"{script} failed ({proc.returncode}):\n"
            f"stdout:\n{proc.stdout[-3000:]}\nstderr:\n{proc.stderr[-3000:]}"
        )
    return proc


def _common_overrides(tree, out, backend="pil"):
    return [
        "DEVICE.PLATFORM", "cpu",
        "DEVICE.COMPUTE_DTYPE", "float32",
        "MODEL.NUM_CLASSES", N_CLASSES,
        "TRAIN.DATASET", tree, "TEST.DATASET", tree,
        "TRAIN.IM_SIZE", 32, "TEST.IM_SIZE", 48,
        "TRAIN.BATCH_SIZE", 2, "TEST.BATCH_SIZE", 4,
        "TRAIN.PRINT_FREQ", 2, "TRAIN.WORKERS", 2,
        # global BN: the default ghost groups of TRAIN.BATCH_SIZE=2 are
        # too noisy to learn in ~24 steps (tuned by hand; SYNCBN is also
        # the collective-in-forward path worth exercising on real data)
        "MODEL.SYNCBN", True,
        # linear-scaled for global batch 16 (ref recipe: 0.1 per 128)
        "OPTIM.BASE_LR", 0.0125, "OPTIM.WARMUP_EPOCHS", 0,
        "RNG_SEED", 1,
        "DATA.BACKEND", backend,
        "OUT_DIR", out,
    ]


@pytest.fixture(scope="module")
def jpeg_tree(tmp_path_factory):
    from tools.make_imagefolder import make_tree

    root = str(tmp_path_factory.mktemp("synthfolder"))
    # 4×48 train (12 steps/epoch at global batch 16), 4×12 val (48 = 1.5
    # eval batches → the ragged-tail masking path runs on real files too)
    make_tree(
        root, n_classes=N_CLASSES, train_per_class=48, val_per_class=12,
        min_size=48, max_size=96, seed=3,
    )
    return root


@pytest.fixture(scope="module")
def trained_run(jpeg_tree, tmp_path_factory):
    """One 2-epoch PIL-backend training run shared by the assertions."""
    out = str(tmp_path_factory.mktemp("realdata_out"))
    _run_cli(
        "train_net.py",
        *_common_overrides(jpeg_tree, out),
        "OPTIM.MAX_EPOCH", 2,
    )
    return out


def _read_metrics(out):
    with open(os.path.join(out, "metrics.jsonl")) as f:
        return [json.loads(line) for line in f]


def test_loss_falls_on_real_jpegs(trained_run):
    recs = _read_metrics(trained_run)
    train = [r for r in recs if r["kind"] == "train"]
    evals = [r for r in recs if r["kind"] == "eval"]
    assert train and len(evals) == 2
    # the meter's within-epoch running average at the last window of the
    # final epoch must sit well below the first window of epoch 0
    assert train[-1]["loss"] < train[0]["loss"]
    # hue-separable classes: a resnet18 must beat 25% chance by a margin
    assert evals[-1]["top1"] > 60.0
    assert evals[-1]["samples"] == N_CLASSES * 12


def test_auto_resume_from_real_jpegs(trained_run, jpeg_tree):
    """Raising MAX_EPOCH resumes from the epoch-1 checkpoint — and the
    resumed run exercises the native C++ decode backend through the CLI."""
    proc = _run_cli(
        "train_net.py",
        *_common_overrides(jpeg_tree, trained_run, backend="native"),
        "OPTIM.MAX_EPOCH", 3,
    )
    log = proc.stderr + proc.stdout
    assert re.search(r"resumed from .*ckpt_ep_001", log), log[-2000:]
    assert os.path.isdir(
        os.path.join(trained_run, "checkpoints", "ckpt_ep_002")
    )


def _eval_top1(proc):
    m = re.search(r"TEST\s+Acc@1\s+([\d.]+)", proc.stderr + proc.stdout)
    assert m, (proc.stdout + proc.stderr)[-2000:]
    return float(m.group(1))


def test_backends_agree_on_eval_metrics(trained_run, jpeg_tree):
    """PIL and native decode produce the same eval accuracy on the same
    checkpoint (pixel differences are bounded by resampler quantization —
    tests/test_native_decode.py — and must not move the metric). The
    uint8 device-normalize path (DATA.DEVICE_NORMALIZE) must match its
    host-normalized float twin EXACTLY — same pixels, normalize merely
    moves in-graph."""
    best = os.path.join(trained_run, "checkpoints", "best")
    top1 = {}
    for name, extra in (
        ("pil", ()),
        ("native", ()),
        ("pil+devnorm", ("DATA.DEVICE_NORMALIZE", "True")),
    ):
        proc = _run_cli(
            "test_net.py",
            *_common_overrides(
                jpeg_tree, trained_run, backend=name.split("+")[0]
            ),
            "MODEL.WEIGHTS", best,
            *extra,
        )
        top1[name] = _eval_top1(proc)
    assert top1["pil"] > 60.0
    # 48 val samples → one flipped prediction = 2.08pp; allow at most one
    assert abs(top1["pil"] - top1["native"]) <= 2.1, top1
    assert top1["pil+devnorm"] == top1["pil"], top1
