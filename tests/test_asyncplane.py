"""Async execution plane (distribuuuu_tpu/asyncplane/, ISSUEs 10+11):
committer ordering (manifest strictly last) + join-barrier correctness,
async-vs-sync checkpoint payload equality, concurrent-eval result parity
with sync eval, compile-cache hit/miss counters (unit + a real cold/warm
restart pair), config validation, the new schema kinds, the run_report
on/off-path checkpoint section, BENCH_r06 indexing — and the hard
contract: async-everything on ≡ fully-sync run bit-identical.

ISSUE 11 additions: the dispatch sequencer (token FIFO + fence-on-switch
+ wedge watchdog), the cross-host commit barrier protocol (single- and
2-process), the subprocess-isolated AOT memory probe (byte-identical to
in-process; coexists with the compile cache), snapshot materialization
of process-spanning leaves, and the deadlock-regression pins: the
async-everything trajectory bit-identical to sync at 8 devices (the
previously-deadlocking configuration) and a real 2-process multi-host
async commit.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.asyncplane import committer, compile_cache, evalloop
from distribuuuu_tpu.telemetry import (
    registry as registry_lib,
    runtime as telemetry_runtime,
    schema,
    spans,
)
from distribuuuu_tpu.utils import checkpoint as ckpt, jsonlog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import bench_history  # noqa: E402
import run_report  # noqa: E402


@pytest.fixture(autouse=True)
def _drain_and_close():
    yield
    try:
        committer.join_commits()
    except committer.AsyncCommitError:
        pass
    spans.close_telemetry()
    jsonlog.close_metrics_log()
    registry_lib.get_registry().reset()


def _tree(seed=0.0):
    return {
        "params": {"w": np.arange(6, dtype=np.float32).reshape(2, 3) + seed},
        "batch_stats": {"m": np.ones(3, np.float32)},
        "opt_state": {"mu": np.zeros(3, np.float32), "lr": 0.1},
        "step": np.int32(7),
    }


# ------------------------------------------------------------- committer
def test_manifest_written_strictly_last(tmp_path, monkeypatch):
    """The PR 3 commit protocol survives going async: at the injectable
    crash-window hook (payload durable, manifest pending) the orbax
    payload files are ALL on disk and MANIFEST.json is NOT."""
    from distribuuuu_tpu.resilience import manifest as manifest_lib
    from distribuuuu_tpu.utils import faults

    cfg.OUT_DIR = str(tmp_path)
    cfg.CHECKPOINT.ASYNC = True
    observed = {}

    def probe(path, epoch):
        payload_files = []
        for dirpath, _, names in os.walk(path):
            payload_files += [n for n in names if n != "MANIFEST.json"]
        observed["payload_files"] = len(payload_files)
        observed["manifest_there"] = os.path.isfile(
            os.path.join(path, "MANIFEST.json")
        )

    monkeypatch.setattr(faults, "maybe_kill_mid_async_save", probe)
    path = ckpt.save_checkpoint(_tree(), 0, 0.5, is_best=False)
    committer.join_commits()
    assert observed["payload_files"] > 0  # orbax payload fully written...
    assert observed["manifest_there"] is False  # ...manifest strictly after
    ok, reason = manifest_lib.verify_checkpoint(path)
    assert ok, reason


def test_join_barrier_serializes_back_to_back_saves():
    """submit joins the previous commit FIRST: at most one commit in
    flight, completion order == submit order even when the first commit
    is slow."""
    order = []

    def slow():
        time.sleep(0.3)
        order.append("a")

    committer.submit_commit("a", slow)
    committer.submit_commit("b", lambda: order.append("b"))
    # the second submit could only start after "a" fully committed
    assert order[0] == "a"
    committer.join_commits()
    assert order == ["a", "b"]


def test_commit_failure_surfaces_at_join():
    def boom():
        raise OSError("disk gone")

    committer.submit_commit("ckpt_ep_042", boom)
    with pytest.raises(committer.AsyncCommitError, match="ckpt_ep_042"):
        committer.join_commits()
    committer.join_commits()  # error consumed; barrier is clean again


def test_async_payload_bitwise_equals_sync(tmp_path):
    tree = _tree()
    cfg.OUT_DIR = str(tmp_path / "async")
    cfg.CHECKPOINT.ASYNC = True
    p_async = ckpt.save_checkpoint(tree, 0, 0.5, is_best=True)
    committer.join_commits()
    cfg.CHECKPOINT.ASYNC = False
    cfg.OUT_DIR = str(tmp_path / "sync")
    p_sync = ckpt.save_checkpoint(tree, 0, 0.5, is_best=True)
    a, b = ckpt.load_checkpoint(p_async), ckpt.load_checkpoint(p_sync)
    la = jax.tree_util.tree_flatten_with_path(a)[0]
    lb = jax.tree_util.tree_flatten_with_path(b)[0]
    assert [k for k, _ in la] == [k for k, _ in lb]
    for (_, va), (_, vb) in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
    # the best side-writes committed (and verify) in both modes
    from distribuuuu_tpu.resilience import manifest as manifest_lib

    for out in ("async", "sync"):
        ok, reason = manifest_lib.verify_checkpoint(
            str(tmp_path / out / "checkpoints" / "best")
        )
        assert ok, (out, reason)


def test_async_multi_host_gate_lifted_with_sequencer(monkeypatch):
    """ISSUE 11: multi-host async commit is ON by default (the
    cross-host barrier handles it); ASYNC.SEQUENCER=False is the
    explicit escape hatch restoring the PR 10 single-host gate."""
    cfg.CHECKPOINT.ASYNC = True
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    assert ckpt.async_enabled() is True  # barrier-backed multi-host
    cfg.ASYNC.SEQUENCER = False
    assert ckpt.async_enabled() is False  # the escape hatch
    cfg.ASYNC.SEQUENCER = True
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    assert ckpt.async_enabled() is True


def test_preempt_save_drains_committer_first(tmp_path):
    """The preemption join barrier: a slow in-flight commit becomes
    durable BEFORE the preempt checkpoint is written synchronously."""
    order = []

    def slow():
        time.sleep(0.2)
        order.append("boundary_commit")

    cfg.OUT_DIR = str(tmp_path)
    cfg.CHECKPOINT.ASYNC = True
    committer.submit_commit("ckpt_ep_000", slow)
    path = ckpt.save_preempt_checkpoint(_tree(), 1, 0.0)
    order.append("preempt_saved")
    assert order == ["boundary_commit", "preempt_saved"]
    from distribuuuu_tpu.resilience import manifest as manifest_lib

    ok, reason = manifest_lib.verify_checkpoint(path)
    assert ok, reason  # the preempt save itself committed synchronously


# -------------------------------------------------------- concurrent eval
def _eval_setup():
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.data.dummy import DummyDataset
    from distribuuuu_tpu.data.loader import Loader
    from distribuuuu_tpu.parallel import mesh as mesh_lib

    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.DUMMY_INPUT = True
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.TRAIN.IM_SIZE = 16
    cfg.TRAIN.BATCH_SIZE = 1
    cfg.RNG_SEED = 1
    mesh = mesh_lib.build_mesh()
    model = trainer.build_model_from_cfg()
    eval_step = trainer.make_eval_step(model, topk=5)
    state = trainer.create_train_state(model, jax.random.key(0), mesh, 16)
    loader = Loader(
        DummyDataset(length=20, size=16), batch_size=8, shuffle=False,
        drop_last=False, workers=2,
    )
    loader.set_epoch(0)
    return trainer, mesh, state, eval_step, loader


def test_concurrent_eval_matches_sync_validate():
    """The worker runs the REAL validate body against a device snapshot:
    result 4-tuple identical to the synchronous call, and the snapshot
    leaves are genuinely independent copies of the live state."""
    from distribuuuu_tpu.utils.logger import get_logger

    trainer, mesh, state, eval_step, loader = _eval_setup()
    sync = trainer.validate(
        loader, mesh, state, eval_step, 0, get_logger(), quiet=True
    )

    conc = evalloop.ConcurrentEval(
        lambda snap, ep: trainer.validate(
            loader, mesh, snap, eval_step, ep, get_logger(),
            quiet=True, watch_preemption=False,
        )
    )
    conc.launch(state, 0)
    assert conc.in_flight
    ep, result, snap = conc.join()
    assert ep == 0 and not conc.in_flight
    assert result == sync
    # the snapshot is a COPY: same values, different buffers
    live_leaf = jax.tree.leaves(state.params)[0]
    snap_leaf = jax.tree.leaves(snap.params)[0]
    np.testing.assert_array_equal(np.asarray(live_leaf), np.asarray(snap_leaf))
    assert snap_leaf is not live_leaf


def test_concurrent_eval_relaunch_guard_and_error_propagation():
    class _S:  # minimal state stand-in with .replace
        params = {"w": np.ones(2, np.float32)}
        batch_stats = {}
        step = 0
        key = None

        def replace(self, **kw):
            return self

    def boom(snap, ep):
        raise RuntimeError("eval exploded")

    conc = evalloop.ConcurrentEval(boom)
    conc.launch(_S(), 3)
    with pytest.raises(RuntimeError, match="eval exploded"):
        conc.join()
    ok = evalloop.ConcurrentEval(lambda snap, ep: (1.0, 2.0, 3.0, 4))
    ok.launch(_S(), 0)
    with pytest.raises(RuntimeError, match="still in flight"):
        ok.launch(_S(), 1)
    assert ok.join()[1] == (1.0, 2.0, 3.0, 4)


# ----------------------------------------------------------- compile cache
def test_compile_cache_config_validation(tmp_path):
    cfg.COMPILE_CACHE.MIN_COMPILE_TIME_S = -1.0
    with pytest.raises(ValueError, match="MIN_COMPILE_TIME_S"):
        compile_cache.setup_from_cfg(cfg)
    config.reset_cfg()
    cfg.COMPILE_CACHE.MAX_SIZE_MB = -5
    with pytest.raises(ValueError, match="MAX_SIZE_MB"):
        compile_cache.setup_from_cfg(cfg)
    config.reset_cfg()
    assert compile_cache.setup_from_cfg(cfg) is None  # disabled → no-op
    cfg.COMPILE_CACHE.ENABLED = True
    cfg.COMPILE_CACHE.DIR = str(tmp_path / "cc")
    cache_dir = compile_cache.setup_from_cfg(cfg)
    assert cache_dir == str(tmp_path / "cc") and os.path.isdir(cache_dir)
    assert jax.config.jax_compilation_cache_dir == cache_dir
    # the knob is authoritative: disabling CLEARS the process-global dir
    config.reset_cfg()
    compile_cache.setup_from_cfg(cfg)
    assert not jax.config.jax_compilation_cache_dir


def test_cache_hit_suppresses_compile_count(tmp_path):
    """Unit-level listener contract (telemetry/runtime.py): the bus
    sequence of a cache hit (cache_hits event → backend_compile
    duration) counts a hit, NOT a compile; a miss still counts the
    compile. kind=\"compile.cache\" records land schema-valid."""
    path = spans.setup_telemetry(str(tmp_path), rank=0)
    reg = registry_lib.get_registry()
    reg.reset()
    # a cache hit: the following backend_compile is a deserialization
    telemetry_runtime._on_event("/jax/compilation_cache/cache_hits")
    telemetry_runtime._on_event_duration(
        "/jax/core/compile/backend_compile_duration", 0.004
    )
    # a cache miss: the following backend_compile is the real thing
    telemetry_runtime._on_event("/jax/compilation_cache/cache_misses")
    telemetry_runtime._on_event_duration(
        "/jax/core/compile/backend_compile_duration", 1.5
    )
    snap = reg.snapshot()["counters"]
    assert snap["jit.cache_hits"] == 1
    assert snap["jit.cache_misses"] == 1
    assert snap["jit.compiles"] == 1  # only the miss compiled
    recs = [json.loads(ln) for ln in open(path).read().splitlines()]
    cache_recs = [r for r in recs if r["kind"] == "compile.cache"]
    assert [r["event"] for r in cache_recs] == ["hit", "miss"]
    for r in cache_recs:
        schema.validate_record(r)
    # exactly ONE kind="compile" record — the real compile, not the hit
    assert len([r for r in recs if r["kind"] == "compile"]) == 1


_CACHE_SCRIPT = """
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
import jax, jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.asyncplane import compile_cache
from distribuuuu_tpu.telemetry import registry as registry_lib, spans
cache_dir, sink_dir = sys.argv[1], sys.argv[2]
config.reset_cfg()
cfg.COMPILE_CACHE.ENABLED = True
cfg.COMPILE_CACHE.DIR = cache_dir
compile_cache.setup_from_cfg(cfg)
spans.setup_telemetry(sink_dir, rank=0)
f = jax.jit(lambda x: (x * 2 + 1).sum())
g = jax.jit(lambda x, y: jnp.tanh(x) @ y)
f(jnp.ones((64, 64))).block_until_ready()
g(jnp.ones((16, 16)), jnp.ones((16, 16))).block_until_ready()
print("COUNTERS " + json.dumps(
    registry_lib.get_registry().snapshot()["counters"]))
"""


def test_warm_restart_hits_cache_zero_compiles(tmp_path):
    """The real thing, across processes: a cold run populates the cache
    (misses, real compiles); a warm rerun of the same programs in a
    FRESH interpreter reports cache hits and ZERO counted compiles."""
    script = tmp_path / "cc_script.py"
    script.write_text(_CACHE_SCRIPT)
    env = {**os.environ, "PYTHONPATH": REPO + os.pathsep
           + os.environ.get("PYTHONPATH", "")}

    def run(tag):
        out = subprocess.run(
            [sys.executable, str(script), str(tmp_path / "cache"),
             str(tmp_path / tag)],
            capture_output=True, text=True, env=env, cwd=REPO, timeout=180,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        line = [ln for ln in out.stdout.splitlines()
                if ln.startswith("COUNTERS ")][-1]
        return json.loads(line[len("COUNTERS "):])

    cold = run("cold")
    assert cold.get("jit.compiles", 0) >= 2  # the two user programs
    assert cold.get("jit.cache_misses", 0) >= 2
    assert cold.get("jit.cache_hits", 0) == 0
    warm = run("warm")
    assert warm.get("jit.compiles", 0) == 0  # everything deserialized
    assert warm.get("jit.cache_hits", 0) >= 2


# ----------------------------------------------------- dispatch sequencer
def test_sequencer_passthrough_when_not_installed():
    from distribuuuu_tpu.asyncplane import sequencer

    sequencer.shutdown()
    assert not sequencer.installed()
    # zero-overhead path: the fn runs directly, fence kwarg ignored
    assert sequencer.dispatch("train", lambda a, b: a + b, 2, 3,
                              fence=True) == 5


def test_sequencer_token_order_fence_and_stats():
    """Two streams hammering the ring: every dispatch serialized, token
    grants strictly FIFO, the stream switches recorded, and the eval
    stream's per-dispatch fence clears its own fence (train never
    inherits an eval fence)."""
    import threading

    import jax.numpy as jnp

    from distribuuuu_tpu.asyncplane import sequencer

    sequencer.shutdown()
    seq = sequencer.install(wedge_timeout=0.0)
    active = []  # critical-section occupancy probe
    overlap = []

    def make(stream, n, fence):
        def run():
            for i in range(n):
                def prog(i=i):
                    active.append(stream)
                    if len(active) > 1:
                        overlap.append(tuple(active))
                    out = jnp.ones(()) * i
                    active.remove(stream)
                    return out
                sequencer.dispatch(stream, prog, fence=fence)
        return run

    threads = [
        threading.Thread(target=make("train", 40, False)),
        threading.Thread(target=make("eval", 40, True)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not any(t.is_alive() for t in threads)
    assert overlap == []  # token held exclusively for every dispatch
    st = seq.snapshot_stats()
    assert st["tokens"] == 80
    assert st["streams"] == {"train": 40, "eval": 40}
    assert st["switches"] >= 1  # the streams interleaved at least once
    assert st["wedges"] == 0
    sequencer.shutdown()


def test_sequencer_wedge_flag_and_record(tmp_path):
    """A dispatcher that holds the token past the watchdog timeout is
    flagged — kind=\"dispatch.wedge\" record + counter — while the other
    stream's dispatch completes once the hold ends (alert, not hang)."""
    import threading
    import time as _time

    from distribuuuu_tpu.asyncplane import sequencer

    path = spans.setup_telemetry(str(tmp_path), rank=0)
    reg = registry_lib.get_registry()
    reg.reset()
    sequencer.shutdown()
    sequencer.install(wedge_timeout=0.2)

    def wedged():
        _time.sleep(0.9)  # the stuck dispatch, holding the token
        return 1

    t = threading.Thread(
        target=lambda: sequencer.dispatch("train", wedged), daemon=True
    )
    t.start()
    _time.sleep(0.1)  # let the wedged stream take the token first
    out = sequencer.dispatch("eval", lambda: 2)  # blocks behind the wedge
    t.join(timeout=30)
    assert out == 2  # the run survived the wedge
    assert reg.snapshot()["counters"].get("dispatch.wedges", 0) >= 1
    spans.close_telemetry()
    recs = [json.loads(ln) for ln in open(path).read().splitlines()]
    wedge = [r for r in recs if r.get("kind") == "dispatch.wedge"]
    assert wedge and wedge[0]["holder"] == "train"
    for r in wedge:
        schema.validate_record(r)
    sequencer.shutdown()


def test_wedge_fault_injection_sleeps_once(monkeypatch):
    from distribuuuu_tpu.utils import faults

    config.reset_cfg()
    cfg.FAULTS.ENABLED = True
    cfg.FAULTS.WEDGE_DISPATCH = 5
    cfg.FAULTS.WEDGE_S = 0.05
    faults.reset()
    import time as _time

    t0 = _time.perf_counter()
    faults.maybe_wedge_dispatch(3)  # below the token index: no-op
    assert _time.perf_counter() - t0 < 0.04
    t0 = _time.perf_counter()
    faults.maybe_wedge_dispatch(5)  # wedges once
    assert _time.perf_counter() - t0 >= 0.05
    t0 = _time.perf_counter()
    faults.maybe_wedge_dispatch(6)  # one-shot: never again
    assert _time.perf_counter() - t0 < 0.04
    config.reset_cfg()
    faults.reset()


# -------------------------------------------- cross-host commit barrier
def _barrier_payload(tmp_path, name="ckpt_ep_007"):
    path = str(tmp_path / "checkpoints" / name)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    return path


def test_multihost_commit_barrier_protocol(tmp_path):
    """Both hosts' shares driven in one process (explicit rank/world):
    the manifest is written strictly AFTER every host arrived, the
    barrier dir is cleaned up, and both hosts emit ckpt.barrier
    records."""
    import threading

    from distribuuuu_tpu.resilience import manifest as manifest_lib

    config.reset_cfg()
    cfg.OUT_DIR = str(tmp_path)
    sink = spans.setup_telemetry(str(tmp_path / "telemetry"), rank=0)
    path = _barrier_payload(tmp_path)
    payload = {"w": np.arange(4.0)}
    order = []

    def write_payload():
        import orbax.checkpoint as ocp

        order.append("payload")
        ocp.PyTreeCheckpointer().save(path, payload, force=True)

    def write_manifest():
        # every host must have arrived BEFORE the manifest commits
        bdir = committer.barrier_dir(path)
        assert os.path.isfile(os.path.join(bdir, "host0.arrived"))
        assert os.path.isfile(os.path.join(bdir, "host1.arrived"))
        order.append("manifest")
        manifest_lib.write_manifest(path, payload, kind="full", epoch=7)

    peer = threading.Thread(
        target=committer.multihost_commit,
        args=(path, None, 7, lambda: None, lambda: None),
        kwargs={"rank": 1, "world": 2}, daemon=True,
    )
    peer.start()
    committer.multihost_commit(
        path, payload, 7, write_payload, write_manifest, rank=0, world=2
    )
    peer.join(timeout=60)
    assert not peer.is_alive()
    assert order == ["payload", "manifest"]  # payload first, marker last
    ok, reason = manifest_lib.verify_checkpoint(path)
    assert ok, reason
    assert not os.path.isdir(committer.barrier_dir(path))  # cleaned up
    spans.close_telemetry()
    recs = [json.loads(ln) for ln in open(sink).read().splitlines()]
    barrier = [r for r in recs if r.get("kind") == "ckpt.barrier"]
    assert {r["host"] for r in barrier} == {0, 1}
    for r in barrier:
        schema.validate_record(r)
        assert r["hosts"] == 2


def test_multihost_barrier_stale_attempt_cannot_satisfy(tmp_path):
    """A barrier dir left by a killed previous attempt is cleared by the
    new attempt's open — stale arrivals never satisfy a fresh save."""
    path = _barrier_payload(tmp_path)
    bdir = committer.barrier_dir(path)
    os.makedirs(bdir, exist_ok=True)
    # stale state from a dead run: OPEN + a peer arrival
    open(os.path.join(bdir, "OPEN"), "w").write("stale")
    open(os.path.join(bdir, "host1.arrived"), "w").write("stale")
    committer.open_barrier(path)
    assert os.path.isfile(os.path.join(bdir, "OPEN"))
    assert not os.path.isfile(os.path.join(bdir, "host1.arrived"))


def test_multihost_barrier_timeout_is_an_error(tmp_path, monkeypatch):
    """A peer that never arrives surfaces as TimeoutError (→
    AsyncCommitError at the join barrier), bounded by
    ASYNC.BARRIER_TIMEOUT_S — never a silent hang."""
    config.reset_cfg()
    cfg.OUT_DIR = str(tmp_path)
    cfg.ASYNC.BARRIER_TIMEOUT_S = 0.3
    path = _barrier_payload(tmp_path)
    with pytest.raises(TimeoutError, match="BARRIER_TIMEOUT"):
        committer.multihost_commit(
            path, None, 7, lambda: None, lambda: None, rank=0, world=2
        )
    config.reset_cfg()


def test_snapshot_tree_materializes_and_refuses():
    """The multi-host snapshot assembly: replicated shards (same index,
    many devices) dedup and cover; split shards assemble in place; a
    cross-host-sharded leaf (local shards cannot cover) refuses with
    MultiHostSnapshotError — the degrade-to-sync trigger."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distribuuuu_tpu.parallel import mesh as mesh_lib

    # the real thing on the live mesh (fully-addressable fast path)
    mesh = mesh_lib.build_mesh()
    arr = jax.device_put(
        jnp.arange(16.0).reshape(4, 4), NamedSharding(mesh, P())
    )
    snap = committer.snapshot_tree({"a": arr, "b": 3})
    np.testing.assert_array_equal(snap["a"], np.arange(16.0).reshape(4, 4))
    assert snap["b"] == 3

    # replicated process-spanning leaf: every local shard is the full
    # array under the same index — assembles, covered once
    full = np.arange(6.0)
    out = committer._assemble_shards(
        (6,), np.float32,
        [((slice(None),), full), ((slice(None),), full)],
    )
    np.testing.assert_array_equal(out, full)

    # locally-sharded leaf: disjoint slices assemble in place
    out = committer._assemble_shards(
        (4,), np.float32,
        [((slice(0, 2),), np.array([0.0, 1.0])),
         ((slice(2, 4),), np.array([2.0, 3.0]))],
    )
    np.testing.assert_array_equal(out, np.arange(4.0))

    # cross-host-sharded: local coverage is partial — refuse
    with pytest.raises(committer.MultiHostSnapshotError, match="2/4"):
        committer._assemble_shards(
            (4,), np.float32, [((slice(0, 2),), np.array([0.0, 1.0]))]
        )


# --------------------------------------- subprocess-isolated AOT probe
def test_memory_probe_subprocess_matches_inprocess():
    """The isolated AOT probe's memory ledger is byte-identical to the
    in-process lowered.compile().memory_analysis() — same StableHLO,
    same SPMD options, a pristine child heap."""
    import jax.numpy as jnp

    from distribuuuu_tpu.telemetry import costmodel

    @jax.jit
    def step(x, w):
        return ((x @ w) ** 2).sum()

    x = jnp.ones((8, 4))
    w = jnp.ones((4, 4))
    lowered = step.lower(x, w)
    inproc = costmodel.normalize_memory(
        lowered.compile().memory_analysis()
    )
    probed = costmodel.probe_memory_subprocess(lowered)
    assert probed == inproc


def test_memory_ledger_coexists_with_compile_cache(tmp_path):
    """PR 10 caveat #2 deleted: with the persistent compilation cache
    ACTIVE, the memory half of the ledger still lands (via the
    subprocess probe) — a run gets the cache AND the HBM ledger."""
    import jax.numpy as jnp

    from distribuuuu_tpu.telemetry import costmodel

    config.reset_cfg()
    cfg.COMPILE_CACHE.ENABLED = True
    cfg.COMPILE_CACHE.DIR = str(tmp_path / "cc")
    compile_cache.setup_from_cfg(cfg)
    assert jax.config.jax_compilation_cache_dir  # the hazard is armed
    try:
        @jax.jit
        def step(x):
            return (x * 2.0).sum()

        analyses = costmodel.analyze_jitted(
            step, (jnp.ones((16, 16)),), with_memory=True
        )
        assert analyses["memory"] is not None
        assert analyses["memory"]["total_bytes"] > 0
    finally:
        config.reset_cfg()
        compile_cache.setup_from_cfg(cfg)  # clears the process-global dir


# ------------------------------------------------- schema / report / index
def test_new_kinds_declared_and_static_check_clean():
    assert "ckpt.async" in schema.KINDS
    assert "compile.cache" in schema.KINDS
    for kind in ("dispatch.token", "dispatch.wedge", "ckpt.barrier"):
        assert kind in schema.KINDS  # ISSUE 11 sequencer/barrier kinds
    for kind in ("dispatch.ring", "ckpt.shard"):
        assert kind in schema.KINDS  # ISSUE 18 pod-scale async kinds
    import check_telemetry_schema as chk

    violations, seen = chk.check_tree(
        os.path.join(REPO, "distribuuuu_tpu")
    )
    assert violations == [], violations
    assert "ckpt.async" in seen and "compile.cache" in seen
    assert {"dispatch.token", "dispatch.wedge", "ckpt.barrier"} <= seen
    assert {"dispatch.ring", "ckpt.shard"} <= seen


def test_run_report_splits_on_vs_off_path(tmp_path):
    """run_report's checkpoint section attributes trainer-blocked
    (snapshot) vs background (commit) seconds and tallies cache events."""
    tdir = tmp_path / "telemetry"
    path = spans.setup_telemetry(str(tdir), rank=0)
    spans.emit_span("step", 1.0, 1.1, track="pipeline", phase="train",
                    epoch=1, batch=0, n=8)
    spans.emit_span("ckpt_snapshot", 2.0, 2.05, track="ckpt",
                    ckpt="ckpt_ep_000", epoch=0)
    spans.emit_span("ckpt_commit", 2.05, 3.25, track="ckpt",
                    ckpt="ckpt_ep_000", epoch=0)
    spans.emit_event("compile.cache", event="hit", hits=1, misses=0)
    spans.emit_event("compile.cache", event="miss", hits=1, misses=1)
    spans.close_telemetry()
    rep = run_report.build_report(str(tmp_path))
    ck = rep["checkpoint"]
    assert ck["snapshots"] == 1 and ck["commits"] == 1
    assert ck["on_path_s"] == pytest.approx(0.05, abs=1e-3)
    assert ck["off_path_s"] == pytest.approx(1.2, abs=1e-3)
    assert ck["on_path_s"] < 0.5 * ck["off_path_s"]  # the acceptance shape
    assert rep["compile_cache"] == {"hits": 1, "misses": 1}
    # sanity: the record forms above are schema-valid
    for r in [json.loads(ln) for ln in open(path).read().splitlines()]:
        schema.validate_record(r)


def test_run_report_sequencer_and_barrier_sections(tmp_path):
    """run_report surfaces the sequencer's token stats (last
    dispatch.token record wins) and the per-host commit-barrier waits."""
    tdir = tmp_path / "telemetry"
    spans.setup_telemetry(str(tdir), rank=0)
    spans.emit_span("step", 1.0, 1.1, track="pipeline", phase="train",
                    epoch=1, batch=0, n=8)
    spans.emit_event("dispatch.token", tokens=10, streams={"train": 9},
                     max_wait_s=0.01, total_wait_s=0.02, fence_waits=1,
                     fence_wait_s=0.005, max_fence_wait_s=0.005,
                     switches=2, wedges=0)
    spans.emit_event("dispatch.token", tokens=40,
                     streams={"train": 30, "eval": 10},
                     max_wait_s=0.02, total_wait_s=0.09, fence_waits=4,
                     fence_wait_s=0.03, max_fence_wait_s=0.01,
                     switches=8, wedges=0)
    spans.emit_event("ckpt.barrier", ckpt="ckpt_ep_000", host=0, hosts=2,
                     wait_s=0.12)
    spans.emit_event("ckpt.barrier", ckpt="ckpt_ep_000", host=1, hosts=2,
                     wait_s=0.34)
    spans.close_telemetry()
    rep = run_report.build_report(str(tmp_path))
    seq = rep["sequencer"]
    assert seq["tokens"] == 40  # the LAST record's running aggregate
    assert seq["streams"] == {"train": 30, "eval": 10}
    assert seq["max_wait_s"] == pytest.approx(0.02)
    assert seq["fence_waits"] == 4
    barrier = rep["checkpoint"]["barrier"]
    assert barrier["hosts"] == 2
    assert barrier["per_host"]["1"]["max_wait_s"] == pytest.approx(0.34)


def test_dispatch_wedge_rule_fires_and_dedups():
    """The monitor's dispatch-wedge rule: aggregator counts
    dispatch.wedge records into the snapshot, the rule fires on the
    first one, dedups while active, and the shipped rules file declares
    it (the RULE_KINDS pin in test_monitor covers the full set)."""
    from distribuuuu_tpu.telemetry import live

    agg = live.LiveAggregator()
    agg.consume([{"kind": "dispatch.wedge", "age_s": 1.2,
                  "holder": "train", "count": 1, "rank": 0}])
    snap = agg.snapshot(window_s=5.0)
    assert snap["dispatch_wedges"] == 1
    engine = live.RuleEngine(
        [live.AlertRule({"kind": "dispatch-wedge", "threshold": 1})],
        interval_s=5.0,
    )
    fired = engine.evaluate(snap)
    assert [f["rule"] for f in fired] == ["dispatch-wedge"]
    # active alert dedups on the next breached window
    agg.consume([{"kind": "dispatch.wedge", "age_s": 2.0,
                  "holder": "eval", "count": 2, "rank": 0}])
    assert engine.evaluate(agg.snapshot(window_s=5.0)) == []
    # wedge-free windows: value 0, rule calm
    assert engine.evaluate(agg.snapshot(window_s=5.0)) == []
    rules = live.load_rules(
        os.path.join(REPO, "config", "monitor_rules.yaml")
    )
    assert "dispatch-wedge" in {r.kind for r in rules}


def test_bench_index_carries_asyncplane_series():
    """BENCH_r06.json indexed (regeneration pin: tests/test_monitor.py
    asserts committed == rebuilt; here the asyncplane series exist and
    none of them rides a throughput-reference name)."""
    index = bench_history.build_index(REPO)
    series = index["series"]
    assert "ckpt_trainer_blocked_s_async" in series
    assert "ckpt_trainer_blocked_s_sync" in series
    assert "warm_restart_compiles" in series
    assert "warm_restart_cache_hits" in series
    # the async run blocks the trainer for less than the sync run did
    blocked_async = series["ckpt_trainer_blocked_s_async"][-1]["value"]
    blocked_sync = series["ckpt_trainer_blocked_s_sync"][-1]["value"]
    assert blocked_async < blocked_sync
    # warm restart: previously-compiled step programs not recompiled
    warm = series["warm_restart_compiles"][-1]["value"]
    cold = series["cold_start_compiles"][-1]["value"]
    assert warm <= max(2.0, 0.1 * cold)
    assert series["warm_restart_cache_hits"][-1]["value"] >= 2
    # r07: the sequencer overhead series (concurrent eval at 8 devices
    # — the previously-deadlocking config — completed and was measured)
    assert series["sequencer_tokens_issued"][-1]["value"] > 0
    assert "sequencer_trainer_blocked_s" in series
    assert "sequencer_token_max_wait_s" in series
    # none of the new series can poison the throughput gate
    mapped = run_report.comparable_metrics(
        json.load(open(os.path.join(REPO, "BENCH_INDEX.json")))
    )
    r5 = json.load(open(os.path.join(REPO, "BENCH_r05.json")))
    assert mapped["img_per_sec"] == r5["parsed"]["value"]


# ---------------------------------------------- cross-host dispatch ring
def _ring_pair(tmp_path, deadline=5.0, detach=600.0):
    """A leader+follower CrossHostRing over one tmp root (both 'hosts'
    in this process — the protocol is pure filesystem, so the ring's
    correctness properties are testable without a second process)."""
    from distribuuuu_tpu.asyncplane import ring as ring_mod

    root = str(tmp_path / "ring")
    lead = ring_mod.CrossHostRing(root, 0, 2, deadline,
                                  detach_after_s=detach)
    lead.open(timeout=1.0)
    follow = ring_mod.CrossHostRing(root, 1, 2, deadline,
                                    detach_after_s=detach)
    follow.open(timeout=1.0)
    return lead, follow


def test_ring_follower_reproduces_leader_order(tmp_path):
    """THE agreement property (tentpole (a)): whatever interleaving the
    leader's two dispatch threads produce, the follower's granted
    (slot, stream) sequence is IDENTICAL — even with adversarial timing
    on the follower's threads. Two SPMD programs from two host threads
    enqueue in ONE per-device order on every host."""
    import threading

    from distribuuuu_tpu.asyncplane import sequencer

    lead_ring, follow_ring = _ring_pair(tmp_path)
    seq_l = sequencer.DispatchSequencer()
    seq_l.attach_ring(lead_ring)
    seq_f = sequencer.DispatchSequencer()
    seq_f.attach_ring(follow_ring)
    n_train, n_eval = 24, 9
    lead_order, follow_order = [], []

    def drive(seq, order, stream, n, delay):
        def run():
            for i in range(n):
                seq.dispatch(stream, lambda: order.append(stream))
                time.sleep(delay)
        return run

    threads = [
        # leader: its local FIFO decides the global order
        threading.Thread(target=drive(seq_l, lead_order, "train",
                                      n_train, 0.001)),
        threading.Thread(target=drive(seq_l, lead_order, "eval",
                                      n_eval, 0.004)),
        # follower: adversarial thread timing — eval hammers early and
        # fast, train lags; the published order must still win
        threading.Thread(target=drive(seq_f, follow_order, "eval",
                                      n_eval, 0.0)),
        threading.Thread(target=drive(seq_f, follow_order, "train",
                                      n_train, 0.002)),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in threads)
    assert len(lead_order) == len(follow_order) == n_train + n_eval
    assert follow_order == lead_order  # ONE order on every host
    assert not follow_ring.wedged and not follow_ring.detached
    assert follow_ring.stats["slots"] == n_train + n_eval
    assert lead_ring.stats["slots"] == n_train + n_eval
    assert lead_ring.stats["switches"] >= 2  # the streams interleaved


def test_ring_deadline_miss_flags_wedge_then_completes(tmp_path):
    """A follower blocked past ASYNC.RING_DEADLINE_S flags
    dispatch.wedge (record + counter + sticky ring-wedged state for the
    trainer's epoch boundary) but keeps waiting — when the leader's
    order finally lands, the run COMPLETES. Degraded, never hung."""
    import threading

    from distribuuuu_tpu.asyncplane import sequencer

    path = spans.setup_telemetry(str(tmp_path / "telemetry"), rank=1)
    reg = registry_lib.get_registry()
    reg.reset()
    lead_ring, follow_ring = _ring_pair(tmp_path, deadline=0.15)
    seq_f = sequencer.DispatchSequencer()
    seq_f.attach_ring(follow_ring)
    out = []

    def late_leader():
        time.sleep(0.5)  # well past the follower's 0.15s deadline
        lead_ring.publish(0, "eval")

    t = threading.Thread(target=late_leader, daemon=True)
    t.start()
    seq_f.dispatch("eval", lambda: out.append("ran"))
    t.join(timeout=30)
    assert out == ["ran"]  # completed once the order landed
    assert follow_ring.wedged and not follow_ring.detached
    assert follow_ring.stats["deadline_misses"] == 1
    assert seq_f._ring_wedged  # the trainer's epoch-boundary signal
    assert reg.snapshot()["counters"].get("dispatch.wedges", 0) >= 1
    spans.close_telemetry()
    recs = [json.loads(ln) for ln in open(path).read().splitlines()]
    wedge = [r for r in recs if r.get("kind") == "dispatch.wedge"]
    assert wedge and "ring slot 0" in wedge[0]["phase"]
    for r in wedge:
        schema.validate_record(r)


def test_ring_detaches_after_leader_silence(tmp_path):
    """Past detach_after_s (the ASYNC.BARRIER_TIMEOUT_S contract) of
    zero leader progress the follower DETACHES to its local FIFO — a
    dead leader costs cross-host agreement, never a hang."""
    from distribuuuu_tpu.asyncplane import sequencer

    lead_ring, follow_ring = _ring_pair(tmp_path, deadline=0.1,
                                        detach=0.3)
    del lead_ring  # the leader never publishes anything
    seq_f = sequencer.DispatchSequencer()
    seq_f.attach_ring(follow_ring)
    t0 = time.perf_counter()
    assert seq_f.dispatch("train", lambda: 42) == 42
    assert time.perf_counter() - t0 < 30  # bounded, not a hang
    assert follow_ring.detached and follow_ring.wedged
    # detached mode: subsequent dispatches grant locally, immediately
    assert seq_f.dispatch("eval", lambda: 7) == 7
    st = follow_ring.snapshot_stats()
    assert st["role"] == "follower" and st["detached"] is True
    assert st["slots"] == 2


def test_ring_validation_and_open_timeout(tmp_path):
    from distribuuuu_tpu.asyncplane import ring as ring_mod
    from distribuuuu_tpu.asyncplane import sequencer

    with pytest.raises(ValueError, match="RING_DEADLINE_S"):
        ring_mod.CrossHostRing(str(tmp_path / "r"), 0, 2, 0.0)
    # follower with no leader: bounded OPEN wait names the knob
    orphan = ring_mod.CrossHostRing(str(tmp_path / "never"), 1, 2, 1.0)
    with pytest.raises(TimeoutError, match="BARRIER_TIMEOUT"):
        orphan.open(timeout=0.2)
    # install_ring requires an installed sequencer
    sequencer.shutdown()
    with pytest.raises(RuntimeError, match="install"):
        sequencer.install_ring(str(tmp_path / "r2"), 0, 2, 1.0)


def test_ring_open_clears_stale_attempt_and_module_api(tmp_path):
    """The leader's open() fresh-clears the ring root — a watermark or
    switch record from a previous (killed) attempt can never leak into
    this run's order. Module API: install_ring attaches to the active
    sequencer, emit_stats rides a schema-valid dispatch.ring record."""
    from distribuuuu_tpu.asyncplane import sequencer

    root = tmp_path / "ring"
    root.mkdir()
    (root / "watermark").write_text('{"seq": 99, "sw": 1}')
    (root / "sw_000000").write_text('{"seq": 0, "stream": "eval"}')
    (root / "OPEN").write_text("stale")
    sink = spans.setup_telemetry(str(tmp_path / "telemetry"), rank=0)
    sequencer.shutdown()
    sequencer.install(wedge_timeout=0.0)
    r = sequencer.install_ring(str(root), 0, 2, 5.0, detach_after_s=1.0)
    assert sequencer.ring_installed()
    assert sorted(os.listdir(root)) == ["OPEN"]  # stale order gone
    assert r.agreed_stream(99) is None
    # idempotent: a re-install keeps the attached ring
    assert sequencer.install_ring(str(root), 0, 2, 5.0) is r
    sequencer.dispatch("train", lambda: 1)
    sequencer.dispatch("eval", lambda: 2)
    # the wedge signal round-trip the trainer boundary uses
    assert not sequencer.ring_wedged()
    _active = sequencer._active
    _active._ring_wedged = True
    assert sequencer.ring_wedged()
    sequencer.clear_ring_wedge()
    assert not sequencer.ring_wedged()
    sequencer.emit_stats(final=True)
    spans.close_telemetry()
    recs = [json.loads(ln) for ln in open(sink).read().splitlines()]
    ring_recs = [r for r in recs if r.get("kind") == "dispatch.ring"]
    assert len(ring_recs) == 1
    assert ring_recs[0]["role"] == "leader"
    assert ring_recs[0]["slots"] == 2
    schema.validate_record(ring_recs[0])
    sequencer.shutdown()


def test_faults_validate_cfg_names_ring_arithmetic():
    """Armed FAULTS knobs with impossible arithmetic refuse at startup,
    naming the knobs AND the units (the satellite-3 contract)."""
    from distribuuuu_tpu.utils import faults

    config.reset_cfg()
    cfg.FAULTS.ENABLED = True
    cfg.FAULTS.WEDGE_RING = 3
    cfg.FAULTS.WEDGE_RING_S = 0.0
    with pytest.raises(ValueError, match="positive number of\\s+seconds"):
        faults.validate_cfg()
    cfg.FAULTS.WEDGE_RING_S = 10.0  # below the 30s default deadline
    with pytest.raises(ValueError) as ei:
        faults.validate_cfg()
    msg = str(ei.value)
    assert "WEDGE_RING_S" in msg and "RING_DEADLINE_S" in msg
    assert "10.0 s" in msg and "30.0 s" in msg  # the arithmetic, named
    cfg.FAULTS.WEDGE_RING_S = 31.0
    faults.validate_cfg()  # now observable: passes
    cfg.FAULTS.WEDGE_RING = -1
    cfg.FAULTS.DROP_SHARD_FILE = 0
    cfg.FAULTS.DROP_SHARD_HOST = -2
    with pytest.raises(ValueError, match="host rank"):
        faults.validate_cfg()
    config.reset_cfg()
    faults.validate_cfg()  # disarmed: no-op
    faults.reset()


def test_wedge_ring_injection_one_shot():
    from distribuuuu_tpu.utils import faults

    config.reset_cfg()
    cfg.FAULTS.ENABLED = True
    cfg.FAULTS.WEDGE_RING = 5
    cfg.FAULTS.WEDGE_RING_S = 0.05
    faults.reset()
    t0 = time.perf_counter()
    faults.maybe_wedge_ring(3)  # below the slot index: no-op
    assert time.perf_counter() - t0 < 0.04
    t0 = time.perf_counter()
    faults.maybe_wedge_ring(5)  # wedges once
    assert time.perf_counter() - t0 >= 0.05
    t0 = time.perf_counter()
    faults.maybe_wedge_ring(6)  # one-shot: never again
    assert time.perf_counter() - t0 < 0.04
    config.reset_cfg()
    faults.reset()


# ------------------------------------------------ sharded multi-host save
def _sharded_fixture(tmp_path, name="ckpt_ep_003"):
    """A hand-built 2-host sharded checkpoint: a float leaf split across
    hosts, a bfloat16 leaf split across hosts, a host-side scalar and
    the optax string format marker (both owned by host 0) — the exact
    leaf species a ZeRO-3 TrainState produces."""
    import jax.numpy as jnp

    w = np.arange(32, dtype=np.float32).reshape(8, 4)
    mu = np.asarray(jnp.arange(6, dtype=jnp.bfloat16))
    marker = "optax_leaves_v1"
    cursor = np.int64(3)
    leaves = [
        {"path": ["params", "w"], "shape": [8, 4], "dtype": "float32"},
        {"path": ["opt", "format"], "shape": [], "dtype": "utf8"},
        {"path": ["opt", "mu", "w"], "shape": [6], "dtype": "bfloat16"},
        {"path": ["cursor"], "shape": [], "dtype": "int64"},
    ]
    raw_marker = np.frombuffer(marker.encode("utf-8"), np.uint8)
    owned0 = {"00000.0": w[:4], "00001.0": raw_marker,
              "00002.0": mu[:3], "00003.0": np.asarray(cursor)}
    shards0 = [
        {"leaf": 0, "key": "00000.0", "index": [[0, 4], [0, 4]],
         "shape": [4, 4], "dtype": "float32"},
        {"leaf": 1, "key": "00001.0", "index": [],
         "shape": [int(raw_marker.size)], "dtype": "utf8"},
        {"leaf": 2, "key": "00002.0", "index": [[0, 3]],
         "shape": [3], "dtype": "bfloat16"},
        {"leaf": 3, "key": "00003.0", "index": [],
         "shape": [], "dtype": "int64"},
    ]
    owned1 = {"00000.1": w[4:], "00002.1": mu[3:]}
    shards1 = [
        {"leaf": 0, "key": "00000.1", "index": [[4, 8], [0, 4]],
         "shape": [4, 4], "dtype": "float32"},
        {"leaf": 2, "key": "00002.1", "index": [[3, 6]],
         "shape": [3], "dtype": "bfloat16"},
    ]
    path = str(tmp_path / "checkpoints" / name)
    os.makedirs(path, exist_ok=True)
    committer.write_host_shards(
        path, 0, 2, owned0,
        {"format": committer.SHARD_FORMAT, "leaves": leaves,
         "shards": shards0},
    )
    committer.write_host_shards(
        path, 1, 2, owned1,
        {"format": committer.SHARD_FORMAT, "leaves": leaves,
         "shards": shards1},
    )
    expect = {"params": {"w": w}, "opt": {"format": marker,
                                          "mu": {"w": mu}},
              "cursor": cursor}
    return path, expect


def test_sharded_roundtrip_bit_identical(tmp_path):
    """Reassembly from per-host shard files is bit-identical for every
    leaf species a ZeRO-3 state holds: split float blocks, split
    bfloat16 (raw-byte round-trip — numpy's npz header cannot carry the
    dtype), host scalars, and the utf8 string format marker."""
    path, expect = _sharded_fixture(tmp_path)
    assert committer.sharded_layout_present(path)
    got = committer.read_sharded_checkpoint(path)
    np.testing.assert_array_equal(got["params"]["w"],
                                  expect["params"]["w"])
    assert got["params"]["w"].dtype == np.float32
    mu = got["opt"]["mu"]["w"]
    assert str(mu.dtype) == "bfloat16"
    assert mu.tobytes() == expect["opt"]["mu"]["w"].tobytes()
    assert got["opt"]["format"] == "optax_leaves_v1"
    assert int(got["cursor"]) == 3
    # load_checkpoint dispatches on the layout, same reassembly
    via_ckpt = ckpt.load_checkpoint(path)
    np.testing.assert_array_equal(via_ckpt["params"]["w"],
                                  expect["params"]["w"])


def test_sharded_restore_refuses_missing_shard(tmp_path):
    """A shard-count mismatch REFUSES, naming the manifest's recorded
    sharding (hosts + the expected file names + which are missing) —
    silently restoring a partial tree is never an option."""
    path, _ = _sharded_fixture(tmp_path)
    os.unlink(os.path.join(path, "shards_host1.npz"))
    with pytest.raises(committer.ShardLayoutError) as ei:
        committer.read_sharded_checkpoint(path)
    msg = str(ei.value)
    assert "hosts=2" in msg and "SHARDS_host0.json" in msg
    assert "shards_host1.npz" in msg and "refusing" in msg


def test_sharded_restore_refuses_layout_drift_and_bad_coverage(tmp_path):
    """Mixed-save shard files (layout drift across hosts) and a layout
    whose shards do not cover a leaf both refuse with the reason."""
    path, _ = _sharded_fixture(tmp_path)
    lay1 = json.load(open(os.path.join(path, "SHARDS_host1.json")))
    drift = dict(lay1)
    drift["leaves"] = list(lay1["leaves"][:-1])  # a different tree spec
    with open(os.path.join(path, "SHARDS_host1.json"), "w") as f:
        json.dump(drift, f)
    with pytest.raises(committer.ShardLayoutError,
                       match="different tree spec"):
        committer.read_sharded_checkpoint(path)
    # coverage hole: host1 stops recording its half of params/w
    cover = dict(lay1)
    cover["shards"] = [m for m in lay1["shards"] if m["leaf"] != 0]
    with open(os.path.join(path, "SHARDS_host1.json"), "w") as f:
        json.dump(cover, f)
    with pytest.raises(committer.ShardLayoutError,
                       match="params/w.*16/32"):
        committer.read_sharded_checkpoint(path)


def test_snapshot_host_shards_ownership_and_refusals(tmp_path):
    """snapshot_host_shards on a host tree: rank 0 owns host-side leaves
    (identical on every host by construction), rank 1 owns none; string
    scalars ride the utf8 tag; object leaves and non-dict containers
    refuse with MultiHostSnapshotError (the sync-collective valve)."""
    tree = {"params": {"w": np.arange(4.0, dtype=np.float32)},
            "opt": {"format": "optax_leaves_v1"},
            "cursor": np.int64(7)}
    owned0, layout0 = committer.snapshot_host_shards(tree, 0)
    owned1, layout1 = committer.snapshot_host_shards(tree, 1)
    assert layout0["leaves"] == layout1["leaves"]  # identical spec
    assert len(owned0) == 3 and owned1 == {}
    path = str(tmp_path / "checkpoints" / "ckpt_ep_000")
    committer.write_host_shards(path, 0, 2, owned0, layout0)
    committer.write_host_shards(path, 1, 2, owned1, layout1)
    got = committer.read_sharded_checkpoint(path)
    np.testing.assert_array_equal(got["params"]["w"],
                                  tree["params"]["w"])
    assert got["opt"]["format"] == "optax_leaves_v1"
    assert int(got["cursor"]) == 7
    with pytest.raises(committer.MultiHostSnapshotError,
                       match="object-dtype"):
        committer.snapshot_host_shards({"bad": np.array(None)}, 0)
    with pytest.raises(committer.MultiHostSnapshotError,
                       match="non-dict"):
        committer.snapshot_host_shards({"t": (np.zeros(2),)}, 0)


def test_manifest_digest_walk_covers_shard_files(tmp_path):
    """The existing MANIFEST digest walk automatically covers the shard
    files: a committed sharded save verifies ok, and a dropped shard
    file FAILS verification — the restart's quarantine + walk-back
    trigger, with no new verification machinery."""
    from distribuuuu_tpu.resilience import manifest as manifest_lib

    path, expect = _sharded_fixture(tmp_path)
    tree = manifest_lib.tree_spec(expect)
    topo = manifest_lib.world_topology(expect)
    manifest_lib.write_manifest(
        path, None, kind="full", epoch=3, tree=tree, topology=topo,
        sharded={"hosts": 2, "files": ["shards_host0.npz",
                                       "shards_host1.npz"]},
    )
    ok, reason = manifest_lib.verify_checkpoint(path)
    assert ok, reason
    man = json.load(open(os.path.join(path, "MANIFEST.json")))
    assert man["sharded"]["hosts"] == 2  # the recorded sharding
    assert set(man["files"]) >= {"shards_host0.npz", "shards_host1.npz",
                                 "SHARDS_host0.json", "SHARDS_host1.json"}
    os.unlink(os.path.join(path, "shards_host1.npz"))
    ok, reason = manifest_lib.verify_checkpoint(path)
    assert not ok and "shards_host1.npz" in reason


def test_drop_shard_file_injection_validates_and_drops(tmp_path):
    """The drop-one-shard-file fault: host index validated against the
    LIVE world (refusal names the range arithmetic), then the victim's
    npz is deleted exactly once."""
    from distribuuuu_tpu.utils import faults

    path, _ = _sharded_fixture(tmp_path)
    config.reset_cfg()
    cfg.FAULTS.ENABLED = True
    cfg.FAULTS.DROP_SHARD_FILE = 3
    cfg.FAULTS.DROP_SHARD_HOST = 5
    faults.reset()
    with pytest.raises(ValueError) as ei:
        faults.maybe_drop_shard_file(path, 3, world=2)
    msg = str(ei.value)
    assert "0 <= host < world (2)" in msg and "shards_host1.npz" in msg
    cfg.FAULTS.DROP_SHARD_HOST = 1
    faults.reset()
    faults.maybe_drop_shard_file(path, 2, world=2)  # wrong epoch: no-op
    assert os.path.isfile(os.path.join(path, "shards_host1.npz"))
    faults.maybe_drop_shard_file(path, 3, world=2)
    assert not os.path.isfile(os.path.join(path, "shards_host1.npz"))
    config.reset_cfg()
    faults.reset()


def test_cross_host_predicate_is_metadata_only():
    """tree_is_cross_host_sharded: False for host trees and
    fully-addressable device arrays (the single-host fast path keeps
    the orbax protocol), no communication, never raises on strings."""
    import jax.numpy as jnp

    tree = {"w": jnp.ones((4, 4)), "s": "optax_leaves_v1",
            "n": np.int64(2)}
    assert committer.tree_is_cross_host_sharded(tree) is False


def test_run_report_ring_and_shard_sections(tmp_path):
    """run_report surfaces the per-host ring waits (dispatch.ring, last
    record per host wins) and the per-host shard-commit durations
    (ckpt.shard) — the satellite-2 sections."""
    tdir = tmp_path / "telemetry"
    path = spans.setup_telemetry(str(tdir), rank=0)
    spans.emit_span("step", 1.0, 1.1, track="pipeline", phase="train",
                    epoch=1, batch=0, n=8)
    spans.emit_event("dispatch.token", tokens=12, streams={"train": 12},
                     max_wait_s=0.01, total_wait_s=0.02, fence_waits=0,
                     fence_wait_s=0.0, max_fence_wait_s=0.0,
                     switches=1, wedges=0)
    spans.emit_event("dispatch.ring", host=0, hosts=2, role="leader",
                     slots=12, switches=3, total_wait_s=0.0,
                     max_wait_s=0.0, deadline_misses=0, wedged=False,
                     detached=False)
    spans.emit_event("dispatch.ring", host=1, hosts=2, role="follower",
                     slots=12, switches=3, total_wait_s=0.8,
                     max_wait_s=0.3, deadline_misses=1, wedged=True,
                     detached=False)
    spans.emit_event("ckpt.shard", ckpt="ckpt_ep_000", host=0, hosts=2,
                     shards=210, bytes=44823923, write_s=0.42)
    spans.emit_event("ckpt.shard", ckpt="ckpt_ep_001", host=0, hosts=2,
                     shards=210, bytes=44823923, write_s=0.38)
    spans.emit_event("ckpt.shard", ckpt="ckpt_ep_000", host=1, hosts=2,
                     shards=80, bytes=44667648, write_s=0.41)
    spans.close_telemetry()
    for r in [json.loads(ln) for ln in open(path).read().splitlines()]:
        schema.validate_record(r)
    rep = run_report.build_report(str(tmp_path))
    ring = rep["sequencer"]["ring"]
    assert ring["hosts"] == 2
    assert ring["per_host"]["0"]["role"] == "leader"
    f = ring["per_host"]["1"]
    assert f["role"] == "follower" and f["wedged"] is True
    assert f["max_wait_s"] == pytest.approx(0.3)
    assert f["deadline_misses"] == 1
    shards = rep["checkpoint"]["shards"]
    assert shards["hosts"] == 2
    h0 = shards["per_host"]["0"]
    assert h0["saves"] == 2 and h0["shards"] == 210
    assert h0["mean_write_s"] == pytest.approx(0.4)
    assert shards["per_host"]["1"]["max_write_s"] == pytest.approx(0.41)


# ------------------------------------------------------- trajectory pin
_PIN_SCRIPT = """
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
ndev = int(sys.argv[4])
if ndev <= 1:
    os.environ.pop("XLA_FLAGS", None)  # ONE device
else:
    # the multi-device mesh — the configuration whose concurrent eval
    # DEADLOCKED before the dispatch sequencer (ISSUE 11)
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=%d" % ndev
    )
import jax
jax.config.update("jax_platforms", "cpu")
import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer

out, mode, cc_dir = sys.argv[1], sys.argv[2], sys.argv[3]
config.reset_cfg()
cfg.MODEL.ARCH = "resnet18"
cfg.MODEL.NUM_CLASSES = 10
cfg.MODEL.DUMMY_INPUT = True
cfg.DEVICE.COMPUTE_DTYPE = "float32"
cfg.TRAIN.BATCH_SIZE = 4
cfg.TRAIN.IM_SIZE = 16
cfg.TRAIN.PRINT_FREQ = 64
cfg.TEST.BATCH_SIZE = 32
cfg.TEST.IM_SIZE = 16
cfg.OPTIM.MAX_EPOCH = 2
cfg.OPTIM.BASE_LR = 0.01
cfg.RNG_SEED = 0
cfg.OUT_DIR = out
if mode == "async":
    # async-EVERYTHING: background ckpt commit + concurrent eval +
    # persistent compile cache, all at once
    cfg.CHECKPOINT.ASYNC = True
    cfg.TRAIN.CONCURRENT_EVAL = True
    cfg.COMPILE_CACHE.ENABLED = True
    cfg.COMPILE_CACHE.DIR = cc_dir
best = trainer.train_model()
assert jax.device_count() == ndev
print(f"PIN_DONE best={best}", flush=True)
"""


def _run_pin_pair(tmp_path, ndev: int):
    """Run the async-everything vs fully-sync pin pair at ``ndev``
    virtual devices; returns ((out_dir, evals), (out_dir, evals))."""
    script = tmp_path / "pin.py"
    script.write_text(_PIN_SCRIPT)
    env = {**os.environ, "PYTHONPATH": REPO + os.pathsep
           + os.environ.get("PYTHONPATH", "")}

    def run(mode):
        out_dir = str(tmp_path / mode)
        proc = subprocess.run(
            [sys.executable, str(script), out_dir, mode,
             str(tmp_path / "cc"), str(ndev)],
            capture_output=True, text=True, env=env, cwd=REPO, timeout=540,
        )
        assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
        if mode == "async":  # the overlapped paths genuinely engaged
            assert "concurrent eval: validate() overlaps" in proc.stderr \
                or "concurrent eval: validate() overlaps" in proc.stdout
            if ndev > 1:  # ...under the sequencer, not a silent degrade
                assert "dispatch sequencer active" in proc.stderr \
                    or "dispatch sequencer active" in proc.stdout
        evals = [
            (r["epoch"], r["loss"], r["top1"], r["topk"], r["samples"])
            for r in (json.loads(ln)
                      for ln in open(os.path.join(out_dir, "metrics.jsonl")))
            if r["kind"] == "eval"
        ]
        return out_dir, evals

    return run("async"), run("sync")


def _assert_pin_pair_identical(out_async, ev_async, out_sync, ev_sync):
    assert len(ev_async) == 2 and ev_async == ev_sync  # per-epoch metrics
    for name in ("ckpt_ep_000", "ckpt_ep_001", "best"):
        a = ckpt.load_checkpoint(os.path.join(out_async, "checkpoints", name))
        b = ckpt.load_checkpoint(os.path.join(out_sync, "checkpoints", name))
        la = jax.tree_util.tree_flatten_with_path(a)[0]
        lb = jax.tree_util.tree_flatten_with_path(b)[0]
        assert [k for k, _ in la] == [k for k, _ in lb]
        for (key, va), (_, vb) in zip(la, lb):
            if "best_acc1" in jax.tree_util.keystr(key):
                # concurrent mode: the boundary save records best as of
                # the PREVIOUS eval (this epoch's is still in flight) —
                # documented lag; the state trees themselves must match
                continue
            np.testing.assert_array_equal(
                np.asarray(va), np.asarray(vb),
                err_msg=f"{name}:{jax.tree_util.keystr(key)}",
            )


@pytest.mark.slow  # two full subprocess trainings; tier-1 budget (ISSUE 16)
def test_async_everything_trajectory_bit_identical(tmp_path):
    """ISSUE 10 hard contract, same style as the PR 7 monitor pin: a run
    with background checkpoint commit + concurrent eval + persistent
    compile cache all ON produces BIT-IDENTICAL checkpoint state trees
    and eval metrics as the fully synchronous run, on one device."""
    (out_async, ev_async), (out_sync, ev_sync) = _run_pin_pair(tmp_path, 1)
    _assert_pin_pair_identical(out_async, ev_async, out_sync, ev_sync)


@pytest.mark.slow  # two 8-device subprocess trainings; tier-1 budget
def test_async_everything_multidevice_bit_identical(tmp_path):
    """ISSUE 11 acceptance: the previously-DEADLOCKING configuration —
    concurrent eval + async save + compile cache on the 8-virtual-device
    CPU mesh — completes under the dispatch sequencer (bounded by the
    subprocess timeout: a regression deadlocks and fails the bound) and
    is bit-identical to the fully synchronous 8-device run."""
    (out_async, ev_async), (out_sync, ev_sync) = _run_pin_pair(tmp_path, 8)
    _assert_pin_pair_identical(out_async, ev_async, out_sync, ev_sync)


_MH_SCRIPT = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer

config.reset_cfg()
cfg.MODEL.ARCH = "resnet18"
cfg.MODEL.NUM_CLASSES = 10
cfg.MODEL.DUMMY_INPUT = True
cfg.DEVICE.COMPUTE_DTYPE = "float32"
cfg.TRAIN.BATCH_SIZE = 2
cfg.TRAIN.IM_SIZE = 16
cfg.TRAIN.PRINT_FREQ = 32
cfg.TEST.BATCH_SIZE = 16
cfg.TEST.IM_SIZE = 16
cfg.OPTIM.MAX_EPOCH = 1
cfg.RNG_SEED = 0
cfg.OUT_DIR = sys.argv[1]
cfg.CHECKPOINT.ASYNC = True
best = trainer.train_model()
print(f"MH_PIN_DONE rank={jax.process_index()} best={best}", flush=True)
"""


@pytest.mark.slow  # real 2-process distributed run; tier-1 budget
def test_multihost_async_commit_two_processes(tmp_path):
    """ISSUE 11 acceptance, the multi-host half: a REAL 2-process run
    with CHECKPOINT.ASYNC commits its checkpoints through the
    cross-host barrier — both hosts complete, every save has a durable
    manifest, the barrier dirs are cleaned up, and each host left its
    ckpt.barrier telemetry record."""
    import socket

    from distribuuuu_tpu.resilience import manifest as manifest_lib

    script = tmp_path / "mh.py"
    script.write_text(_MH_SCRIPT)
    out = str(tmp_path / "out")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    procs, logs = [], []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.update(
            MASTER_ADDR="127.0.0.1", COORDINATOR_PORT=str(port),
            WORLD_SIZE="2", RANK=str(rank),
            PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        log = open(tmp_path / f"mh{rank}.log", "w+")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, str(script), out], env=env, cwd=REPO,
            stdout=log, stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    for p, log in zip(procs, logs):
        try:
            p.wait(timeout=420)
        except subprocess.TimeoutExpired:
            p.kill()
            p.wait()
        log.seek(0)
        outs.append(log.read())
        log.close()
    assert [p.returncode for p in procs] == [0, 0], outs[0][-3000:]
    assert all("MH_PIN_DONE" in o for o in outs)
    # every committed save verifies; no barrier litter left behind
    ckpt_dir = os.path.join(out, "checkpoints")
    names = sorted(os.listdir(ckpt_dir))
    assert "ckpt_ep_000" in names
    assert not any(n.endswith(".barrier") for n in names)
    for name in names:
        if name.startswith("."):
            continue
        ok, reason = manifest_lib.verify_checkpoint(
            os.path.join(ckpt_dir, name)
        )
        assert ok, (name, reason)
    # each host recorded its barrier wait
    barrier_hosts = set()
    tdir = os.path.join(out, "telemetry")
    for fname in os.listdir(tdir):
        if not fname.endswith(".jsonl"):
            continue
        for ln in open(os.path.join(tdir, fname)):
            try:
                r = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if r.get("kind") == "ckpt.barrier":
                schema.validate_record(r)
                barrier_hosts.add(r["host"])
    assert barrier_hosts == {0, 1}
