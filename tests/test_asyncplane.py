"""Async execution plane (distribuuuu_tpu/asyncplane/, ISSUE 10):
committer ordering (manifest strictly last) + join-barrier correctness,
async-vs-sync checkpoint payload equality, concurrent-eval result parity
with sync eval, compile-cache hit/miss counters (unit + a real cold/warm
restart pair), config validation, the new schema kinds, the run_report
on/off-path checkpoint section, BENCH_r06 indexing — and the hard
contract: async-everything on ≡ fully-sync run bit-identical.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import jax

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.asyncplane import committer, compile_cache, evalloop
from distribuuuu_tpu.telemetry import (
    registry as registry_lib,
    runtime as telemetry_runtime,
    schema,
    spans,
)
from distribuuuu_tpu.utils import checkpoint as ckpt, jsonlog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import bench_history  # noqa: E402
import run_report  # noqa: E402


@pytest.fixture(autouse=True)
def _drain_and_close():
    yield
    try:
        committer.join_commits()
    except committer.AsyncCommitError:
        pass
    spans.close_telemetry()
    jsonlog.close_metrics_log()
    registry_lib.get_registry().reset()


def _tree(seed=0.0):
    return {
        "params": {"w": np.arange(6, dtype=np.float32).reshape(2, 3) + seed},
        "batch_stats": {"m": np.ones(3, np.float32)},
        "opt_state": {"mu": np.zeros(3, np.float32), "lr": 0.1},
        "step": np.int32(7),
    }


# ------------------------------------------------------------- committer
def test_manifest_written_strictly_last(tmp_path, monkeypatch):
    """The PR 3 commit protocol survives going async: at the injectable
    crash-window hook (payload durable, manifest pending) the orbax
    payload files are ALL on disk and MANIFEST.json is NOT."""
    from distribuuuu_tpu.resilience import manifest as manifest_lib
    from distribuuuu_tpu.utils import faults

    cfg.OUT_DIR = str(tmp_path)
    cfg.CHECKPOINT.ASYNC = True
    observed = {}

    def probe(path, epoch):
        payload_files = []
        for dirpath, _, names in os.walk(path):
            payload_files += [n for n in names if n != "MANIFEST.json"]
        observed["payload_files"] = len(payload_files)
        observed["manifest_there"] = os.path.isfile(
            os.path.join(path, "MANIFEST.json")
        )

    monkeypatch.setattr(faults, "maybe_kill_mid_async_save", probe)
    path = ckpt.save_checkpoint(_tree(), 0, 0.5, is_best=False)
    committer.join_commits()
    assert observed["payload_files"] > 0  # orbax payload fully written...
    assert observed["manifest_there"] is False  # ...manifest strictly after
    ok, reason = manifest_lib.verify_checkpoint(path)
    assert ok, reason


def test_join_barrier_serializes_back_to_back_saves():
    """submit joins the previous commit FIRST: at most one commit in
    flight, completion order == submit order even when the first commit
    is slow."""
    order = []

    def slow():
        time.sleep(0.3)
        order.append("a")

    committer.submit_commit("a", slow)
    committer.submit_commit("b", lambda: order.append("b"))
    # the second submit could only start after "a" fully committed
    assert order[0] == "a"
    committer.join_commits()
    assert order == ["a", "b"]


def test_commit_failure_surfaces_at_join():
    def boom():
        raise OSError("disk gone")

    committer.submit_commit("ckpt_ep_042", boom)
    with pytest.raises(committer.AsyncCommitError, match="ckpt_ep_042"):
        committer.join_commits()
    committer.join_commits()  # error consumed; barrier is clean again


def test_async_payload_bitwise_equals_sync(tmp_path):
    tree = _tree()
    cfg.OUT_DIR = str(tmp_path / "async")
    cfg.CHECKPOINT.ASYNC = True
    p_async = ckpt.save_checkpoint(tree, 0, 0.5, is_best=True)
    committer.join_commits()
    cfg.CHECKPOINT.ASYNC = False
    cfg.OUT_DIR = str(tmp_path / "sync")
    p_sync = ckpt.save_checkpoint(tree, 0, 0.5, is_best=True)
    a, b = ckpt.load_checkpoint(p_async), ckpt.load_checkpoint(p_sync)
    la = jax.tree_util.tree_flatten_with_path(a)[0]
    lb = jax.tree_util.tree_flatten_with_path(b)[0]
    assert [k for k, _ in la] == [k for k, _ in lb]
    for (_, va), (_, vb) in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
    # the best side-writes committed (and verify) in both modes
    from distribuuuu_tpu.resilience import manifest as manifest_lib

    for out in ("async", "sync"):
        ok, reason = manifest_lib.verify_checkpoint(
            str(tmp_path / out / "checkpoints" / "best")
        )
        assert ok, (out, reason)


def test_async_multi_host_degrades_to_sync(tmp_path, monkeypatch):
    cfg.CHECKPOINT.ASYNC = True
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    assert ckpt.async_enabled() is False  # collective saves stay sync
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    assert ckpt.async_enabled() is True


def test_preempt_save_drains_committer_first(tmp_path):
    """The preemption join barrier: a slow in-flight commit becomes
    durable BEFORE the preempt checkpoint is written synchronously."""
    order = []

    def slow():
        time.sleep(0.2)
        order.append("boundary_commit")

    cfg.OUT_DIR = str(tmp_path)
    cfg.CHECKPOINT.ASYNC = True
    committer.submit_commit("ckpt_ep_000", slow)
    path = ckpt.save_preempt_checkpoint(_tree(), 1, 0.0)
    order.append("preempt_saved")
    assert order == ["boundary_commit", "preempt_saved"]
    from distribuuuu_tpu.resilience import manifest as manifest_lib

    ok, reason = manifest_lib.verify_checkpoint(path)
    assert ok, reason  # the preempt save itself committed synchronously


# -------------------------------------------------------- concurrent eval
def _eval_setup():
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.data.dummy import DummyDataset
    from distribuuuu_tpu.data.loader import Loader
    from distribuuuu_tpu.parallel import mesh as mesh_lib

    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.DUMMY_INPUT = True
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.TRAIN.IM_SIZE = 16
    cfg.TRAIN.BATCH_SIZE = 1
    cfg.RNG_SEED = 1
    mesh = mesh_lib.build_mesh()
    model = trainer.build_model_from_cfg()
    eval_step = trainer.make_eval_step(model, topk=5)
    state = trainer.create_train_state(model, jax.random.key(0), mesh, 16)
    loader = Loader(
        DummyDataset(length=20, size=16), batch_size=8, shuffle=False,
        drop_last=False, workers=2,
    )
    loader.set_epoch(0)
    return trainer, mesh, state, eval_step, loader


def test_concurrent_eval_matches_sync_validate():
    """The worker runs the REAL validate body against a device snapshot:
    result 4-tuple identical to the synchronous call, and the snapshot
    leaves are genuinely independent copies of the live state."""
    from distribuuuu_tpu.utils.logger import get_logger

    trainer, mesh, state, eval_step, loader = _eval_setup()
    sync = trainer.validate(
        loader, mesh, state, eval_step, 0, get_logger(), quiet=True
    )

    conc = evalloop.ConcurrentEval(
        lambda snap, ep: trainer.validate(
            loader, mesh, snap, eval_step, ep, get_logger(),
            quiet=True, watch_preemption=False,
        )
    )
    conc.launch(state, 0)
    assert conc.in_flight
    ep, result, snap = conc.join()
    assert ep == 0 and not conc.in_flight
    assert result == sync
    # the snapshot is a COPY: same values, different buffers
    live_leaf = jax.tree.leaves(state.params)[0]
    snap_leaf = jax.tree.leaves(snap.params)[0]
    np.testing.assert_array_equal(np.asarray(live_leaf), np.asarray(snap_leaf))
    assert snap_leaf is not live_leaf


def test_concurrent_eval_relaunch_guard_and_error_propagation():
    class _S:  # minimal state stand-in with .replace
        params = {"w": np.ones(2, np.float32)}
        batch_stats = {}
        step = 0
        key = None

        def replace(self, **kw):
            return self

    def boom(snap, ep):
        raise RuntimeError("eval exploded")

    conc = evalloop.ConcurrentEval(boom)
    conc.launch(_S(), 3)
    with pytest.raises(RuntimeError, match="eval exploded"):
        conc.join()
    ok = evalloop.ConcurrentEval(lambda snap, ep: (1.0, 2.0, 3.0, 4))
    ok.launch(_S(), 0)
    with pytest.raises(RuntimeError, match="still in flight"):
        ok.launch(_S(), 1)
    assert ok.join()[1] == (1.0, 2.0, 3.0, 4)


# ----------------------------------------------------------- compile cache
def test_compile_cache_config_validation(tmp_path):
    cfg.COMPILE_CACHE.MIN_COMPILE_TIME_S = -1.0
    with pytest.raises(ValueError, match="MIN_COMPILE_TIME_S"):
        compile_cache.setup_from_cfg(cfg)
    config.reset_cfg()
    cfg.COMPILE_CACHE.MAX_SIZE_MB = -5
    with pytest.raises(ValueError, match="MAX_SIZE_MB"):
        compile_cache.setup_from_cfg(cfg)
    config.reset_cfg()
    assert compile_cache.setup_from_cfg(cfg) is None  # disabled → no-op
    cfg.COMPILE_CACHE.ENABLED = True
    cfg.COMPILE_CACHE.DIR = str(tmp_path / "cc")
    cache_dir = compile_cache.setup_from_cfg(cfg)
    assert cache_dir == str(tmp_path / "cc") and os.path.isdir(cache_dir)
    assert jax.config.jax_compilation_cache_dir == cache_dir
    # the knob is authoritative: disabling CLEARS the process-global dir
    config.reset_cfg()
    compile_cache.setup_from_cfg(cfg)
    assert not jax.config.jax_compilation_cache_dir


def test_cache_hit_suppresses_compile_count(tmp_path):
    """Unit-level listener contract (telemetry/runtime.py): the bus
    sequence of a cache hit (cache_hits event → backend_compile
    duration) counts a hit, NOT a compile; a miss still counts the
    compile. kind=\"compile.cache\" records land schema-valid."""
    path = spans.setup_telemetry(str(tmp_path), rank=0)
    reg = registry_lib.get_registry()
    reg.reset()
    # a cache hit: the following backend_compile is a deserialization
    telemetry_runtime._on_event("/jax/compilation_cache/cache_hits")
    telemetry_runtime._on_event_duration(
        "/jax/core/compile/backend_compile_duration", 0.004
    )
    # a cache miss: the following backend_compile is the real thing
    telemetry_runtime._on_event("/jax/compilation_cache/cache_misses")
    telemetry_runtime._on_event_duration(
        "/jax/core/compile/backend_compile_duration", 1.5
    )
    snap = reg.snapshot()["counters"]
    assert snap["jit.cache_hits"] == 1
    assert snap["jit.cache_misses"] == 1
    assert snap["jit.compiles"] == 1  # only the miss compiled
    recs = [json.loads(ln) for ln in open(path).read().splitlines()]
    cache_recs = [r for r in recs if r["kind"] == "compile.cache"]
    assert [r["event"] for r in cache_recs] == ["hit", "miss"]
    for r in cache_recs:
        schema.validate_record(r)
    # exactly ONE kind="compile" record — the real compile, not the hit
    assert len([r for r in recs if r["kind"] == "compile"]) == 1


_CACHE_SCRIPT = """
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
import jax, jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.asyncplane import compile_cache
from distribuuuu_tpu.telemetry import registry as registry_lib, spans
cache_dir, sink_dir = sys.argv[1], sys.argv[2]
config.reset_cfg()
cfg.COMPILE_CACHE.ENABLED = True
cfg.COMPILE_CACHE.DIR = cache_dir
compile_cache.setup_from_cfg(cfg)
spans.setup_telemetry(sink_dir, rank=0)
f = jax.jit(lambda x: (x * 2 + 1).sum())
g = jax.jit(lambda x, y: jnp.tanh(x) @ y)
f(jnp.ones((64, 64))).block_until_ready()
g(jnp.ones((16, 16)), jnp.ones((16, 16))).block_until_ready()
print("COUNTERS " + json.dumps(
    registry_lib.get_registry().snapshot()["counters"]))
"""


def test_warm_restart_hits_cache_zero_compiles(tmp_path):
    """The real thing, across processes: a cold run populates the cache
    (misses, real compiles); a warm rerun of the same programs in a
    FRESH interpreter reports cache hits and ZERO counted compiles."""
    script = tmp_path / "cc_script.py"
    script.write_text(_CACHE_SCRIPT)
    env = {**os.environ, "PYTHONPATH": REPO + os.pathsep
           + os.environ.get("PYTHONPATH", "")}

    def run(tag):
        out = subprocess.run(
            [sys.executable, str(script), str(tmp_path / "cache"),
             str(tmp_path / tag)],
            capture_output=True, text=True, env=env, cwd=REPO, timeout=180,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        line = [ln for ln in out.stdout.splitlines()
                if ln.startswith("COUNTERS ")][-1]
        return json.loads(line[len("COUNTERS "):])

    cold = run("cold")
    assert cold.get("jit.compiles", 0) >= 2  # the two user programs
    assert cold.get("jit.cache_misses", 0) >= 2
    assert cold.get("jit.cache_hits", 0) == 0
    warm = run("warm")
    assert warm.get("jit.compiles", 0) == 0  # everything deserialized
    assert warm.get("jit.cache_hits", 0) >= 2


# ------------------------------------------------- schema / report / index
def test_new_kinds_declared_and_static_check_clean():
    assert "ckpt.async" in schema.KINDS
    assert "compile.cache" in schema.KINDS
    import check_telemetry_schema as chk

    violations, seen = chk.check_tree(
        os.path.join(REPO, "distribuuuu_tpu")
    )
    assert violations == [], violations
    assert "ckpt.async" in seen and "compile.cache" in seen


def test_run_report_splits_on_vs_off_path(tmp_path):
    """run_report's checkpoint section attributes trainer-blocked
    (snapshot) vs background (commit) seconds and tallies cache events."""
    tdir = tmp_path / "telemetry"
    path = spans.setup_telemetry(str(tdir), rank=0)
    spans.emit_span("step", 1.0, 1.1, track="pipeline", phase="train",
                    epoch=1, batch=0, n=8)
    spans.emit_span("ckpt_snapshot", 2.0, 2.05, track="ckpt",
                    ckpt="ckpt_ep_000", epoch=0)
    spans.emit_span("ckpt_commit", 2.05, 3.25, track="ckpt",
                    ckpt="ckpt_ep_000", epoch=0)
    spans.emit_event("compile.cache", event="hit", hits=1, misses=0)
    spans.emit_event("compile.cache", event="miss", hits=1, misses=1)
    spans.close_telemetry()
    rep = run_report.build_report(str(tmp_path))
    ck = rep["checkpoint"]
    assert ck["snapshots"] == 1 and ck["commits"] == 1
    assert ck["on_path_s"] == pytest.approx(0.05, abs=1e-3)
    assert ck["off_path_s"] == pytest.approx(1.2, abs=1e-3)
    assert ck["on_path_s"] < 0.5 * ck["off_path_s"]  # the acceptance shape
    assert rep["compile_cache"] == {"hits": 1, "misses": 1}
    # sanity: the record forms above are schema-valid
    for r in [json.loads(ln) for ln in open(path).read().splitlines()]:
        schema.validate_record(r)


def test_bench_index_carries_asyncplane_series():
    """BENCH_r06.json indexed (regeneration pin: tests/test_monitor.py
    asserts committed == rebuilt; here the asyncplane series exist and
    none of them rides a throughput-reference name)."""
    index = bench_history.build_index(REPO)
    series = index["series"]
    assert "ckpt_trainer_blocked_s_async" in series
    assert "ckpt_trainer_blocked_s_sync" in series
    assert "warm_restart_compiles" in series
    assert "warm_restart_cache_hits" in series
    # the async run blocks the trainer for less than the sync run did
    blocked_async = series["ckpt_trainer_blocked_s_async"][-1]["value"]
    blocked_sync = series["ckpt_trainer_blocked_s_sync"][-1]["value"]
    assert blocked_async < blocked_sync
    # warm restart: previously-compiled step programs not recompiled
    warm = series["warm_restart_compiles"][-1]["value"]
    cold = series["cold_start_compiles"][-1]["value"]
    assert warm <= max(2.0, 0.1 * cold)
    assert series["warm_restart_cache_hits"][-1]["value"] >= 2
    # none of the new series can poison the throughput gate
    mapped = run_report.comparable_metrics(
        json.load(open(os.path.join(REPO, "BENCH_INDEX.json")))
    )
    r5 = json.load(open(os.path.join(REPO, "BENCH_r05.json")))
    assert mapped["img_per_sec"] == r5["parsed"]["value"]


# ------------------------------------------------------- trajectory pin
_PIN_SCRIPT = """
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # ONE device: concurrent eval must run
import jax
jax.config.update("jax_platforms", "cpu")
import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer

out, mode, cc_dir = sys.argv[1], sys.argv[2], sys.argv[3]
config.reset_cfg()
cfg.MODEL.ARCH = "resnet18"
cfg.MODEL.NUM_CLASSES = 10
cfg.MODEL.DUMMY_INPUT = True
cfg.DEVICE.COMPUTE_DTYPE = "float32"
cfg.TRAIN.BATCH_SIZE = 4
cfg.TRAIN.IM_SIZE = 16
cfg.TRAIN.PRINT_FREQ = 64
cfg.TEST.BATCH_SIZE = 32
cfg.TEST.IM_SIZE = 16
cfg.OPTIM.MAX_EPOCH = 2
cfg.OPTIM.BASE_LR = 0.01
cfg.RNG_SEED = 0
cfg.OUT_DIR = out
if mode == "async":
    # async-EVERYTHING: background ckpt commit + concurrent eval +
    # persistent compile cache, all at once
    cfg.CHECKPOINT.ASYNC = True
    cfg.TRAIN.CONCURRENT_EVAL = True
    cfg.COMPILE_CACHE.ENABLED = True
    cfg.COMPILE_CACHE.DIR = cc_dir
best = trainer.train_model()
assert jax.device_count() == 1
print(f"PIN_DONE best={best}", flush=True)
"""


def test_async_everything_trajectory_bit_identical(tmp_path):
    """ISSUE 10 hard contract, same style as the PR 7 monitor pin: a run
    with background checkpoint commit + concurrent eval + persistent
    compile cache all ON produces BIT-IDENTICAL checkpoint state trees
    and eval metrics as the fully synchronous run. Fresh single-device
    subprocesses: concurrent eval is gated to one device (two
    multi-device programs dispatched from two threads can deadlock
    their collectives), so the 8-virtual-device test mesh would
    silently degrade it — a real 1-device run is the only honest pin."""
    script = tmp_path / "pin.py"
    script.write_text(_PIN_SCRIPT)
    env = {**os.environ, "PYTHONPATH": REPO + os.pathsep
           + os.environ.get("PYTHONPATH", "")}

    def run(mode):
        out_dir = str(tmp_path / mode)
        proc = subprocess.run(
            [sys.executable, str(script), out_dir, mode,
             str(tmp_path / "cc")],
            capture_output=True, text=True, env=env, cwd=REPO, timeout=540,
        )
        assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
        if mode == "async":  # the overlapped paths genuinely engaged
            assert "concurrent eval: validate() overlaps" in proc.stderr                 or "concurrent eval: validate() overlaps" in proc.stdout
        evals = [
            (r["epoch"], r["loss"], r["top1"], r["topk"], r["samples"])
            for r in (json.loads(ln)
                      for ln in open(os.path.join(out_dir, "metrics.jsonl")))
            if r["kind"] == "eval"
        ]
        return out_dir, evals

    out_async, ev_async = run("async")
    out_sync, ev_sync = run("sync")
    assert len(ev_async) == 2 and ev_async == ev_sync  # per-epoch metrics
    for name in ("ckpt_ep_000", "ckpt_ep_001", "best"):
        a = ckpt.load_checkpoint(os.path.join(out_async, "checkpoints", name))
        b = ckpt.load_checkpoint(os.path.join(out_sync, "checkpoints", name))
        la = jax.tree_util.tree_flatten_with_path(a)[0]
        lb = jax.tree_util.tree_flatten_with_path(b)[0]
        assert [k for k, _ in la] == [k for k, _ in lb]
        for (key, va), (_, vb) in zip(la, lb):
            if "best_acc1" in jax.tree_util.keystr(key):
                # concurrent mode: the boundary save records best as of
                # the PREVIOUS eval (this epoch's is still in flight) —
                # documented lag; the state trees themselves must match
                continue
            np.testing.assert_array_equal(
                np.asarray(va), np.asarray(vb),
                err_msg=f"{name}:{jax.tree_util.keystr(key)}",
            )
