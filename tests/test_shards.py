"""Sharded dataset subsystem (data/shards/): format round-trip, the
topology-independent order, truncation recovery, and exact mid-epoch
resume — the PR 4 acceptance gates.

Pinned invariants:
  - pack→read round-trips are BYTE-identical to the source imagefolder
    (stored bytes verbatim; decoded+augmented arrays equal bit-for-bit);
  - the global sample order is a function of (seed, epoch) alone —
    interleaving the per-rank streams of dp∈{1,2,4} reproduces the same
    global order bit-identically;
  - a truncated shard (footer gone) recovers its index by forward scan
    and the lost records flow through DATA.SKIP_CORRUPT instead of
    killing the epoch;
  - mid-epoch save/restore through the REAL preempt-checkpoint path
    continues at the exact next batch and lands on the uninterrupted
    run's trajectory.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest
from PIL import Image

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.data.loader import Loader
from distribuuuu_tpu.data.shards import (
    ShardDataset,
    ShardReadError,
    WindowShuffleSampler,
    global_order,
    pack_imagefolder,
    read_shard_index,
    read_shard_manifest,
    verify_split,
)
from distribuuuu_tpu.utils import faults, preempt

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    preempt.reset()
    yield
    faults.reset()
    preempt.reset()


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    """Small imagefolder + packed shards (multiple shards per split)."""
    root = tmp_path_factory.mktemp("shards_corpus")
    src = root / "src"
    rng = np.random.default_rng(0)
    for split, per_cls in (("train", 16), ("val", 4)):
        for cls in ("class_a", "class_b", "class_c"):
            d = src / split / cls
            d.mkdir(parents=True)
            for i in range(per_cls):
                arr = rng.integers(0, 255, (40, 50, 3)).astype(np.uint8)
                Image.fromarray(arr).save(d / f"img_{i}.jpg", quality=90)
    out = root / "shards"
    pack_imagefolder(str(src), str(out), target_bytes=16 * 1024)
    return {"src": str(src), "shards": str(out)}


# ------------------------------------------------------------------- format
def test_pack_roundtrip_byte_identical(corpus):
    from distribuuuu_tpu.data.imagefolder import ImageFolderDataset

    ds = ShardDataset(corpus["shards"], "train", im_size=32, train=True,
                      base_seed=3, backend="pil")
    ifd = ImageFolderDataset(corpus["src"], "train", im_size=32, train=True,
                             base_seed=3, backend="pil")
    assert len(ds) == len(ifd) == 48
    assert ds.classes == ifd.classes
    man = read_shard_manifest(os.path.join(corpus["shards"], "train"))
    assert len(man["shards"]) > 1  # the tiny target really rolled shards
    for i in (0, 7, 23, 47):
        image_bytes, label, key = ds.record(i)
        path, src_label = ifd.samples[i]
        with open(path, "rb") as f:
            assert image_bytes == f.read()  # encoded bytes verbatim
        assert label == src_label
        assert key == os.path.relpath(path, os.path.join(corpus["src"], "train"))
    # decoded + augmented arrays are bit-identical (same PIL ops, same
    # (seed, epoch, idx) RNG stream)
    ds.set_epoch_seed(2)
    ifd.set_epoch_seed(2)
    for i in (0, 23, 47):
        a, la = ds[i]
        b, lb = ifd[i]
        np.testing.assert_array_equal(a, b)
        assert la == lb


def test_verify_split_certifies_and_catches_corruption(corpus, tmp_path):
    import shutil

    ok, problems = verify_split(os.path.join(corpus["shards"], "val"))
    assert ok, problems
    # flip one byte in a shard → sha256 mismatch names the shard
    work = tmp_path / "val"
    shutil.copytree(os.path.join(corpus["shards"], "val"), work)
    man = read_shard_manifest(str(work))
    victim = work / man["shards"][0]["file"]
    data = bytearray(victim.read_bytes())
    data[len(data) // 2] ^= 0xFF
    victim.write_bytes(bytes(data))
    ok, problems = verify_split(str(work))
    assert not ok
    assert any(man["shards"][0]["file"] in p for p in problems), problems


def test_make_shards_cli_pack_and_verify(corpus, tmp_path):
    out = tmp_path / "cli_shards"
    r = subprocess.run(
        [sys.executable, "tools/make_shards.py", "--src", corpus["src"],
         "--out", str(out), "--splits", "val", "--shard-mb", "0.02"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(
        [sys.executable, "tools/make_shards.py", "--out", str(out),
         "--verify", "--splits", "val"],
        capture_output=True, text=True, cwd=REPO, timeout=300,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    assert json.loads(r.stdout.strip().splitlines()[-1])["ok"] is True


def test_native_batch_matches_imagefolder_native(corpus):
    from distribuuuu_tpu import native
    from distribuuuu_tpu.data.imagefolder import ImageFolderDataset

    if not (native.available() and native.has_mem_api()):
        pytest.skip(f"native kernel unavailable: {native.build_error()}")
    ds = ShardDataset(corpus["shards"], "train", im_size=32, train=True,
                      base_seed=3)
    ifd = ImageFolderDataset(corpus["src"], "train", im_size=32, train=True,
                             base_seed=3)
    ds.set_epoch_seed(1)
    ifd.set_epoch_seed(1)
    idxs = [0, 5, 17, 46]
    imgs, labels = ds.load_batch(idxs, n_threads=2)
    ref, ref_labels = ifd.load_batch(idxs, n_threads=2)
    # same kernel, same geometry draws, byte-identical inputs → identical
    np.testing.assert_array_equal(imgs, ref)
    np.testing.assert_array_equal(labels, ref_labels)


# -------------------------------------------------------------------- order
def test_global_order_is_seed_epoch_permutation():
    o = global_order(100, seed=7, epoch=3, block=8, window=16)
    assert sorted(o.tolist()) == list(range(100))
    np.testing.assert_array_equal(
        o, global_order(100, seed=7, epoch=3, block=8, window=16)
    )
    assert not np.array_equal(
        o, global_order(100, seed=7, epoch=4, block=8, window=16)
    )
    assert not np.array_equal(
        o, global_order(100, seed=8, epoch=3, block=8, window=16)
    )
    # degenerate knobs reduce to a plain uniform permutation domain
    tiny = global_order(5, seed=0, epoch=0, block=1, window=5)
    assert sorted(tiny.tolist()) == list(range(5))


def test_global_order_identical_across_dp_1_2_4():
    """The acceptance gate: interleaving the per-rank streams of any world
    size reproduces the SAME global order — dp=1/2/4 see one stream."""
    n, seed, epoch = 96, 11, 2
    ref = global_order(n, seed, epoch, block=8, window=32)
    for world in (1, 2, 4):
        samplers = [
            WindowShuffleSampler(n, world, r, seed=seed, block=8, window=32)
            for r in range(world)
        ]
        inter = np.empty((n,), np.int64)
        for r, s in enumerate(samplers):
            s.set_epoch(epoch)
            inter[r::world] = s.indices()
        np.testing.assert_array_equal(inter, ref)


def test_order_state_identity():
    s = WindowShuffleSampler(48, 1, 0, seed=5, block=4, window=8)
    s.set_epoch(3)
    st = s.order_state()
    assert st["epoch"] == 3 and st["seed"] == 5
    # JSON round-trip clean (it rides the preempt checkpoint as JSON)
    assert json.loads(json.dumps(st)) == json.loads(json.dumps(st))
    s2 = WindowShuffleSampler(48, 4, 2, seed=5, block=4, window=8)
    s2.set_epoch(3)
    assert json.loads(json.dumps(s2.order_state())) == json.loads(json.dumps(st))


# ------------------------------------------------------- truncation recovery
def _truncated_copy(corpus, tmp_path):
    import shutil

    work = tmp_path / "trunc"
    shutil.copytree(os.path.join(corpus["shards"], "train"), work / "train")
    man = read_shard_manifest(str(work / "train"))
    victim = work / "train" / man["shards"][-1]["file"]
    size = victim.stat().st_size
    with open(victim, "r+b") as f:
        f.truncate(size * 6 // 10)
    return str(work), man


def test_truncated_shard_recovers_index_and_skips_lost_records(
    corpus, tmp_path
):
    work, man = _truncated_copy(corpus, tmp_path)
    victim = os.path.join(work, "train", man["shards"][-1]["file"])
    offsets, recovered = read_shard_index(victim)
    assert recovered and 0 < len(offsets) < man["shards"][-1]["records"]

    ds = ShardDataset(work, "train", im_size=16, train=True, backend="pil")
    assert len(ds) == man["num_records"]  # manifest length is authoritative
    ds[0]  # early records decode fine
    with pytest.raises(ShardReadError, match="lost to truncation"):
        ds[len(ds) - 1]

    # the loader's SKIP_CORRUPT path substitutes and completes the epoch
    cfg.DATA.RETRIES = 0
    loader = Loader(ds, batch_size=8, shuffle=True, drop_last=True,
                    workers=2, seed=0)
    loader.set_epoch(0)
    batches = list(loader)
    assert len(batches) == len(loader)
    assert all(b["image"].shape[0] == 8 for b in batches)


def test_faults_truncate_shard_knob(corpus, tmp_path):
    import shutil

    work = tmp_path / "injected"
    shutil.copytree(os.path.join(corpus["shards"], "train"), work / "train")
    man = read_shard_manifest(str(work / "train"))
    cfg.FAULTS.ENABLED = True
    cfg.FAULTS.TRUNCATE_SHARD = len(man["shards"]) - 1
    ds = ShardDataset(str(work), "train", im_size=16, train=True,
                      backend="pil")
    victim = work / "train" / man["shards"][-1]["file"]
    assert victim.stat().st_size < man["shards"][-1]["size"]  # damaged
    with pytest.raises(ShardReadError):
        ds[len(ds) - 1]
    ds[0]  # surviving prefix still serves


# ------------------------------------------------------- exact resume cursor
def _shard_loader(corpus, **kw):
    ds = ShardDataset(corpus["shards"], "train", im_size=16, train=True,
                      base_seed=0, backend="pil")
    return Loader(ds, batch_size=8, shuffle=True, drop_last=True, workers=2,
                  seed=7, **kw)


def test_loader_state_roundtrip_resumes_exact_stream(corpus):
    cfg.DATA.SHARDS_BLOCK = 4
    cfg.DATA.SHARDS_WINDOW = 16
    loader = _shard_loader(corpus)
    assert loader.can_save_state()
    loader.set_epoch(1)
    full = [b["label"].tolist() for b in loader]
    sd = loader.state_dict(2)
    assert sd["cursor"] == 2 * 8  # world size 1 in tests
    sd = json.loads(json.dumps(sd))  # the checkpoint round-trip is JSON

    fresh = _shard_loader(corpus)
    skip = fresh.load_state_dict(sd)
    assert skip == 2 and fresh.resume_skip(1) == 2 and fresh.resume_skip(0) == 0
    fresh.set_epoch(1)
    assert [b["label"].tolist() for b in fresh] == full[2:]
    # one-shot: the next epoch iterates whole
    fresh.set_epoch(2)
    assert len(list(fresh)) == len(fresh)


def test_loader_state_rejects_drifted_identity(corpus):
    cfg.DATA.SHARDS_BLOCK = 4
    cfg.DATA.SHARDS_WINDOW = 16
    loader = _shard_loader(corpus)
    loader.set_epoch(0)
    sd = json.loads(json.dumps(loader.state_dict(1)))

    wrong_seed = _shard_loader(corpus)
    wrong_seed.sampler.seed = 99  # ≙ RNG_SEED changed between runs
    with pytest.raises(ValueError, match="order identity"):
        wrong_seed.load_state_dict(sd)

    other = dict(sd)
    other["num_records"] = 7
    with pytest.raises(ValueError, match="corpus changed"):
        _shard_loader(corpus).load_state_dict(other)

    other = dict(sd)
    other["format"] = "imagefolder"
    with pytest.raises(ValueError, match="live pipeline"):
        _shard_loader(corpus).load_state_dict(other)


def test_data_state_checkpoint_encoding_roundtrip():
    from distribuuuu_tpu.utils import checkpoint as ckpt

    sd = {"v": 1, "format": "shards", "epoch": 3, "cursor": 1024,
          "order": {"seed": 5, "rng_state": {"state": 2**100}}}
    arr = ckpt.encode_data_state(sd)
    assert arr.dtype == np.uint8
    assert ckpt.decode_data_state(arr) == sd
    assert ckpt.decode_data_state(np.zeros((4,), np.uint8)) is None


# --------------------------------------------- trajectory through the trainer
@pytest.mark.slow  # 83s: two full trainings + preempt subprocess; tier-1 budget
def test_midepoch_preempt_resume_matches_uninterrupted(corpus, tmp_path):
    """The tentpole acceptance: preempt at batch k through the REAL
    signal → preempt-checkpoint → resume chain (FAULTS.PREEMPT_AT_BATCH,
    save_preempt_checkpoint with the embedded cursor, _resume +
    train_epoch continuation), then compare against the uninterrupted run.
    The continued epoch consumes batch k+1 next and the final state lands
    on the same trajectory."""
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.parallel import mesh as mesh_lib
    from distribuuuu_tpu.utils import checkpoint as ckpt
    from distribuuuu_tpu.utils.logger import get_logger
    from distribuuuu_tpu.utils.optim import construct_optimizer

    def setup(out_dir):
        config.reset_cfg()
        cfg.MODEL.ARCH = "resnet18"
        cfg.MODEL.NUM_CLASSES = 3
        cfg.DEVICE.COMPUTE_DTYPE = "float32"
        cfg.TRAIN.IM_SIZE = 16
        cfg.TRAIN.BATCH_SIZE = 1  # ×8 virtual devices = per-host batch 8
        cfg.TRAIN.PRINT_FREQ = 16
        cfg.DATA.FORMAT = "shards"
        cfg.DATA.SHARDS_BLOCK = 4
        cfg.DATA.SHARDS_WINDOW = 16
        cfg.RNG_SEED = 1
        cfg.OUT_DIR = str(out_dir)
        mesh = mesh_lib.build_mesh()
        model = trainer.build_model_from_cfg()
        step = trainer.make_train_step(model, construct_optimizer(), topk=3)
        state = trainer.create_train_state(model, jax.random.key(0), mesh, 16)
        return trainer, mesh, model, step, state

    logger = get_logger()

    # ---- reference: one uninterrupted epoch ----
    trn, mesh, model, step, state = setup(tmp_path / "ref")
    ref_loader = _shard_loader(corpus)
    state, interrupted, done = trn.train_epoch(
        loader=ref_loader, mesh=mesh, state=state, train_step=step,
        epoch=0, logger=logger,
    )
    assert not interrupted and done == len(ref_loader)
    ref_params = jax.tree.map(np.asarray, jax.device_get(state.params))

    # ---- interrupted run: identical init, preempted at batch 2 ----
    trn, mesh, model, step, state = setup(tmp_path / "run")
    cfg.FAULTS.ENABLED = True
    cfg.FAULTS.PREEMPT_EPOCH = 0
    cfg.FAULTS.PREEMPT_AT_BATCH = 2
    preempt.install()
    loader = _shard_loader(corpus)
    state, interrupted, done = trn.train_epoch(
        loader=loader, mesh=mesh, state=state, train_step=step,
        epoch=0, logger=logger,
    )
    assert interrupted and 0 < done < len(loader)
    ckpt.save_preempt_checkpoint(
        trn._state_tree(state), 0, 0.0,
        data_state=loader.state_dict(done),
    )

    # ---- "restart": fresh template state, resume + continue ----
    preempt.reset()
    cfg.FAULTS.ENABLED = False
    fresh = trn.create_train_state(model, jax.random.key(0), mesh, 16)
    resumed, start_epoch, _, _, data_state = trn._resume(fresh, mesh)
    assert start_epoch == 0 and int(resumed.step) == done
    assert data_state is not None and data_state["cursor"] == done * 8
    loader2 = _shard_loader(corpus)
    trn._arm_exact_resume(loader2, data_state, start_epoch, logger)
    assert loader2.resume_skip(0) == done  # consumes batch done+1 next
    resumed, interrupted, total = trn.train_epoch(
        loader=loader2, mesh=mesh, state=resumed, train_step=step,
        epoch=0, logger=logger,
    )
    assert not interrupted and total == len(loader2)
    got_params = jax.tree.map(np.asarray, jax.device_get(resumed.params))
    # float32 state round-trips orbax exactly; same batches, same step
    # math → the trajectories coincide (well inside the lockstep tolerance
    # of tests/test_resilience.py)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=0, atol=1e-5),
        ref_params, got_params,
    )


# ----------------------------------------------------------- pp bubble (sat)
def test_pp_bubble_logged_once_per_schedule(tmp_path):
    from distribuuuu_tpu.parallel import pp
    from distribuuuu_tpu.utils import jsonlog

    pp._logged_schedules.clear()
    jsonlog.setup_metrics_log(str(tmp_path))
    pp.log_bubble_fraction(4, 8)
    pp.log_bubble_fraction(4, 8)  # dedup: one record per distinct (S, M)
    pp.log_bubble_fraction(2, 2)
    jsonlog.close_metrics_log()
    recs = [
        json.loads(ln)
        for ln in open(tmp_path / "metrics.jsonl").read().splitlines()
        if json.loads(ln)["kind"] == "pp_bubble"
    ]
    assert len(recs) == 2
    assert recs[0]["stages"] == 4 and recs[0]["microbatches"] == 8
    assert recs[0]["ticks"] == 11 and abs(recs[0]["bubble"] - 3 / 11) < 1e-4
    assert abs(recs[1]["bubble"] - 1 / 3) < 1e-3
