"""Unified telemetry layer (distribuuuu_tpu/telemetry/, ISSUE 5): span
nesting, registry aggregation, the per-rank sink + jsonlog mirror,
Perfetto export over merged rank files, run_report math + the
--compare regression gate, the kind-schema static check, and — the hard
contract — trajectory neutrality (telemetry on ≡ off bit-identically).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import telemetry
from distribuuuu_tpu.telemetry import (
    export,
    registry as registry_lib,
    schema,
    spans,
)
from distribuuuu_tpu.utils import jsonlog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import run_report  # noqa: E402  (tools/, needs the path insert above)


@pytest.fixture(autouse=True)
def _close_sinks():
    yield
    spans.close_telemetry()
    jsonlog.close_metrics_log()
    registry_lib.get_registry().reset()


def _read(path):
    return [json.loads(ln) for ln in open(path).read().splitlines()]


# ---------------------------------------------------------------- spans
def test_noop_before_setup():
    spans.emit_event("stall", age_s=1.0, count=1)  # must not raise
    spans.emit_span("step", 0.0, 1.0)
    with spans.span("anything"):
        pass
    assert not spans.enabled()


def test_sink_opens_with_clock_anchor(tmp_path):
    path = spans.setup_telemetry(str(tmp_path), rank=3)
    assert os.path.basename(path) == "rank00003.jsonl"
    recs = _read(path)
    assert recs[0]["kind"] == "clock"
    assert recs[0]["rank"] == 3
    # anchor pair sampled back-to-back: unix and mono describe ~the same
    # instant (their difference equals the clocks' offset, checked via a
    # fresh pair)
    off_now = time.time() - time.perf_counter()
    off_anchor = recs[0]["unix"] - recs[0]["mono"]
    assert abs(off_now - off_anchor) < 5.0


def test_span_nesting_and_timestamps(tmp_path):
    path = spans.setup_telemetry(str(tmp_path), rank=0)
    with spans.span("outer", track="t"):
        time.sleep(0.01)
        with spans.span("inner", foo=7):
            time.sleep(0.01)
    recs = [r for r in _read(path) if r["kind"] == "span"]
    inner = next(r for r in recs if r["name"] == "inner")
    outer = next(r for r in recs if r["name"] == "outer")
    assert inner["parent"] == "outer" and inner["depth"] == 1
    assert inner["track"] == "t"  # inherited from the enclosing span
    assert "depth" not in outer
    assert inner["foo"] == 7
    # containment: inner ⊆ outer in time
    assert outer["t0"] <= inner["t0"]
    assert inner["t0"] + inner["dur"] <= outer["t0"] + outer["dur"] + 1e-6
    assert outer["dur"] >= 0.02 - 1e-3
    for r in recs:
        schema.validate_record(r)


def test_emit_span_precomputed_stamps(tmp_path):
    path = spans.setup_telemetry(str(tmp_path), rank=0)
    spans.emit_span("step", 10.0, 10.5, track="pipeline", phase="train",
                    epoch=1, batch=4, n=32)
    (rec,) = [r for r in _read(path) if r["kind"] == "span"]
    assert rec["t0"] == 10.0 and rec["dur"] == 0.5
    assert rec["track"] == "pipeline" and rec["batch"] == 4
    schema.validate_record(rec)


def test_jsonlog_mirrors_rank_local_kinds_on_non_primary(tmp_path):
    """The satellite-3 fix: before the telemetry layer, a non-primary
    process's stall/data_error records vanished (jsonlog's sink is
    primary-only). With a per-rank sink open they survive."""
    jsonlog.setup_metrics_log(str(tmp_path), primary=False)  # rank > 0
    path = spans.setup_telemetry(str(tmp_path / "telemetry"), rank=2)
    jsonlog.metrics_log("stall", age_s=12.5, last="epoch 1 batch 7", count=1)
    jsonlog.metrics_log("data_error", index=9, attempts=3, error="IOError: x")
    # primary sink never existed; the rank file has both records
    assert not os.path.exists(tmp_path / "metrics.jsonl")
    recs = _read(path)
    kinds = [r["kind"] for r in recs]
    assert "stall" in kinds and "data_error" in kinds
    stall = next(r for r in recs if r["kind"] == "stall")
    assert stall["rank"] == 2 and stall["age_s"] == 12.5
    for r in recs:
        schema.validate_record(r)


def test_timeline_not_mirrored(tmp_path):
    """timeline stays primary-only (the exporter reads metrics.jsonl);
    mirroring would double every batch record in rank 0's file."""
    jsonlog.setup_metrics_log(str(tmp_path), primary=True)
    path = spans.setup_telemetry(str(tmp_path / "telemetry"), rank=0)
    jsonlog.timeline_log("train", 1, 0, 16, get0=1.0, get1=1.1)
    assert any(
        r["kind"] == "timeline" for r in _read(tmp_path / "metrics.jsonl")
    )
    assert not any(r["kind"] == "timeline" for r in _read(path))


def test_emit_overhead_is_bounded(tmp_path):
    """The ISSUE 5 'overhead bounded and measured' clause: one span write
    costs ~30µs on this container (measured); the bound here is a loose
    CI-jitter-proof ceiling. At ~5 spans/batch that is ≪1% of any real
    step, and the writes happen outside the measured intervals anyway."""
    spans.setup_telemetry(str(tmp_path), rank=0)
    n = 2000
    t0 = time.perf_counter()
    for i in range(n):
        spans.emit_span("step", 1.0, 1.1, track="pipeline",
                        phase="train", epoch=1, batch=i, n=8)
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 500e-6, f"emit_span cost {per_call * 1e6:.0f}µs/call"


# -------------------------------------------------------------- registry
def test_registry_aggregation():
    reg = registry_lib.Registry()
    reg.counter("c").inc()
    reg.counter("c").inc(2.5)
    reg.gauge("g").set(1.0)
    reg.gauge("g").set(4.0)
    h = reg.histogram("h")
    for v in range(1, 101):
        h.observe(float(v))
    snap = reg.snapshot()
    assert snap["counters"]["c"] == 3.5
    assert snap["gauges"]["g"] == 4.0
    hs = snap["histograms"]["h"]
    assert hs["count"] == 100 and hs["min"] == 1.0 and hs["max"] == 100.0
    assert hs["p50"] == 50.0 and hs["p90"] == 90.0 and hs["p99"] == 99.0
    assert hs["mean"] == pytest.approx(50.5)


def test_registry_instruments_are_shared_by_name():
    reg = registry_lib.Registry()
    assert reg.counter("x") is reg.counter("x")
    assert reg.histogram("y") is reg.histogram("y")


def test_registry_snapshot_lands_in_sink(tmp_path):
    path = spans.setup_telemetry(str(tmp_path), rank=0)
    registry_lib.get_registry().counter("jit.compiles").inc(4)
    telemetry.emit_snapshot(epoch=2)
    (rec,) = [r for r in _read(path) if r["kind"] == "registry"]
    assert rec["counters"]["jit.compiles"] == 4.0
    assert rec["epoch"] == 2
    schema.validate_record(rec)


def test_serve_metrics_ride_the_shared_registry():
    """Satellite 1: ServeMetrics' meters ARE registry instruments (one
    schema for serve and train) while the serve_bench JSON fields stay
    exactly what they were."""
    from distribuuuu_tpu.serve.metrics import ServeMetrics

    m = ServeMetrics()
    m.record_batch(3, 4, 0.010, [0.001, 0.002, 0.003])
    m.record_rejection()
    snap = m.snapshot()
    assert snap["requests"] == 3 and snap["rejected"] == 1
    assert snap["batches"] == 1 and snap["batch_occupancy"] == 0.75
    assert snap["p50_ms"] == 2.0 and snap["p99_ms"] == 3.0
    assert snap["mean_batch_ms"] == 10.0
    # the instruments live in a Registry and snapshot through its schema
    rsnap = m.registry.snapshot()
    assert rsnap["counters"]["serve.requests"] == 3.0
    assert rsnap["histograms"]["serve.latency_s"]["count"] == 3


# ---------------------------------------------------------------- schema
def test_validate_record_rejects_undeclared_and_drifted():
    with pytest.raises(schema.SchemaError, match="undeclared"):
        schema.validate_record({"kind": "no_such_kind"})
    with pytest.raises(schema.SchemaError, match="missing required"):
        schema.validate_record({"kind": "stall", "age_s": 1.0})  # no count
    schema.validate_record({"kind": "stall", "age_s": 1.0, "count": 2})


def test_schema_static_check_is_clean_on_the_repo():
    """Tier-1 gate: every emit call site in distribuuuu_tpu/ declares its
    kind (satellite 2). Run as the CLI so the check itself is covered."""
    out = subprocess.run(
        [sys.executable, os.path.join("tools", "check_telemetry_schema.py")],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 violation(s)" in out.stdout


def test_schema_static_check_flags_violations(tmp_path):
    import check_telemetry_schema as checker

    bad = tmp_path / "pkg"
    bad.mkdir()
    (bad / "mod.py").write_text(
        "from distribuuuu_tpu.utils.jsonlog import metrics_log\n"
        "metrics_log('totally_new_kind', x=1)\n"        # undeclared
        "metrics_log('stall', age_s=1.0)\n"             # drifted: no count
        "k = 'dyn'\nmetrics_log(k, x=1)\n"              # dynamic outside sinks
    )
    violations, seen = checker.check_tree(str(bad))
    msgs = "\n".join(violations)
    assert "undeclared kind 'totally_new_kind'" in msgs
    assert "drifted" in msgs and "count" in msgs
    assert "non-literal kind" in msgs
    assert len(violations) == 3
    # a clean file passes
    good = tmp_path / "ok"
    good.mkdir()
    (good / "mod.py").write_text(
        "metrics_log('stall', age_s=1.0, count=2)\n"
    )
    violations, seen = checker.check_tree(str(good))
    assert violations == [] and seen == {"stall"}


# ------------------------------------------------- synthetic rank fixtures
def _write_rank(tmp_path, rank, step_ms, *, extra=None, anchor=1000.0):
    """A synthetic rank file: clock anchor + one 'step' span per entry of
    ``step_ms`` (spaced 1s apart on the mono clock) + optional extras."""
    tdir = tmp_path / "telemetry"
    tdir.mkdir(exist_ok=True)
    path = tdir / f"rank{rank:05d}.jsonl"
    recs = [{"kind": "clock", "rank": rank, "t": 0.0,
             "unix": 1_700_000_000.0, "mono": anchor}]
    for i, ms in enumerate(step_ms):
        t0 = anchor + i * 1.0
        recs.append({
            "kind": "span", "rank": rank, "t": 0.0, "v": 1, "name": "step",
            "t0": t0, "dur": ms / 1e3, "track": "pipeline",
            "phase": "train", "epoch": 1, "batch": i, "n": 8,
        })
        recs.append({
            "kind": "span", "rank": rank, "t": 0.0, "v": 1, "name": "wait",
            "t0": t0 - 0.05, "dur": 0.05, "track": "pipeline",
            "phase": "train", "epoch": 1, "batch": i,
        })
    for r in extra or []:
        recs.append({"rank": rank, "t": 0.0, **r})
    with open(path, "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    return path


# ---------------------------------------------------------------- export
def test_perfetto_export_merges_ranks_onto_one_timebase(tmp_path):
    # two ranks with DIFFERENT mono origins but one unix timebase: the
    # exporter must land both on the same wall-clock axis
    _write_rank(tmp_path, 0, [100.0, 100.0], anchor=1000.0)
    _write_rank(tmp_path, 1, [100.0, 100.0], anchor=500_000.0,
                extra=[{"kind": "stall", "age_s": 9.0, "count": 1,
                        "t": 1_700_000_001.0},
                       {"kind": "compile", "event": "backend_compile",
                        "dur_s": 0.25, "mono": 500_000.5}])
    trace = export.merge_trace(str(tmp_path))
    evs = trace["traceEvents"]
    # trace-event schema: every event has name/ph/pid; X events add ts+dur
    for e in evs:
        assert {"name", "ph", "pid"} <= set(e)
        if e["ph"] == "X":
            assert isinstance(e["ts"], float) and e["dur"] >= 0.0
    pids = {e["pid"] for e in evs if e["ph"] == "X"}
    assert pids == {0, 1}  # one track group per rank
    # the same (batch, name) slice on both ranks maps to ~the same unix µs
    def ts_of(pid, batch):
        return next(e["ts"] for e in evs
                    if e["ph"] == "X" and e["pid"] == pid
                    and e["name"] == "step" and e["args"]["batch"] == batch)
    assert ts_of(0, 0) == pytest.approx(ts_of(1, 0), abs=1.0)
    assert ts_of(0, 0) == pytest.approx(1_700_000_000.0 * 1e6, abs=1e3)
    # instants + compile slices made it over with their own tracks
    assert any(e["ph"] == "i" and e["name"] == "stall" for e in evs)
    assert any(e["ph"] == "X" and e["name"] == "compile" for e in evs)
    # process/thread name metadata for Perfetto's track labels
    names = [e for e in evs if e["ph"] == "M" and e["name"] == "process_name"]
    assert {n["args"]["name"] for n in names} == {"rank 0", "rank 1"}


def test_export_includes_primary_timeline_records(tmp_path):
    _write_rank(tmp_path, 0, [100.0], anchor=1000.0)
    with open(tmp_path / "metrics.jsonl", "w") as f:
        f.write(json.dumps({
            "kind": "timeline", "t": 0.0, "v": 1, "phase": "train",
            "epoch": 1, "batch": 0, "n": 8, "dec0": 1000.0, "dec1": 1000.2,
            "asm1": 1000.25, "get0": 1000.3, "get1": 1000.31,
            "put0": 1000.31, "put1": 1000.33, "step0": 1000.33,
            "step1": 1000.43,
        }) + "\n")
    trace = export.merge_trace(str(tmp_path))
    evs = [e for e in trace["traceEvents"] if e.get("cat") == "timeline"]
    assert {e["name"] for e in evs} == {
        "wait", "h2d", "step", "decode", "assemble"
    }
    dec = next(e for e in evs if e["name"] == "decode")
    # placed through rank 0's anchor: mono 1000.0 ≡ unix 1.7e9
    assert dec["ts"] == pytest.approx(1_700_000_000.0 * 1e6, abs=1e3)
    assert dec["dur"] == pytest.approx(0.2 * 1e6, rel=1e-6)


def test_export_raises_without_any_telemetry(tmp_path):
    with pytest.raises(FileNotFoundError):
        export.merge_trace(str(tmp_path))


# ------------------------------------------------------------- run_report
def test_run_report_percentiles_and_straggler_skew(tmp_path):
    # rank 0 steady at 100ms; rank 1 a 2× straggler at 200ms
    _write_rank(tmp_path, 0, [100.0] * 10)
    _write_rank(tmp_path, 1, [200.0] * 10,
                extra=[{"kind": "stall", "age_s": 30.0, "count": 1},
                       {"kind": "data_error", "index": 5, "attempts": 3,
                        "error": "x"},
                       {"kind": "compile", "event": "backend_compile",
                        "dur_s": 1.5, "mono": 1.0},
                       {"kind": "span", "v": 1, "name": "ckpt_save",
                        "t0": 50.0, "dur": 2.0, "track": "ckpt"}])
    rep = run_report.build_report(str(tmp_path))
    assert rep["n_ranks"] == 2 and rep["step_source"] == "step"
    assert rep["per_rank_step"]["0"]["p50_ms"] == 100.0
    assert rep["per_rank_step"]["1"]["p50_ms"] == 200.0
    assert rep["step"]["count"] == 20
    assert rep["step"]["p99_ms"] == 200.0
    assert rep["straggler_skew"] == 2.0
    # wait spans: 50ms wait per ~1s window on each rank
    assert 0.02 < rep["data_wait_frac"] < 0.12
    assert rep["events"] == {"stall": 1, "data_error": 1, "nonfinite": 0}
    assert rep["recompiles"] == {"count": 1, "wall_s": 1.5}
    assert rep["checkpoint"]["saves"] == 1
    assert rep["checkpoint"]["save_max_s"] == 2.0


def test_run_report_fold_window_fallback(tmp_path):
    tdir = tmp_path / "telemetry"
    tdir.mkdir()
    recs = [{"kind": "clock", "rank": 0, "t": 0.0, "unix": 0.0, "mono": 0.0}]
    for i in range(4):
        recs.append({
            "kind": "span", "rank": 0, "t": 0.0, "v": 1,
            "name": "fold_window", "t0": i * 1.0, "dur": 0.8,
            "track": "pipeline", "phase": "train", "epoch": 1,
            "batch": i * 8, "n": 8,
        })
    with open(tdir / "rank00000.jsonl", "w") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")
    rep = run_report.build_report(str(tmp_path))
    assert rep["step_source"] == "fold_window"
    assert rep["step"]["p50_ms"] == 100.0  # 0.8s window / 8 steps


def test_run_report_compare_gate_both_ways(tmp_path):
    _write_rank(tmp_path, 0, [100.0] * 10)
    rep = run_report.build_report(str(tmp_path))
    base_ok = dict(rep)  # identical → PASS
    cmp = run_report.compare(rep, base_ok, tol_pct=10.0, tol_overrides={})
    assert cmp["ok"] and cmp["checked"] >= 2
    # a baseline whose steps were 2× faster → current is a regression
    fast = json.loads(json.dumps(rep))
    for q in ("p50_ms", "p90_ms", "p99_ms"):
        fast["step"][q] = rep["step"][q] / 2.0
    cmp = run_report.compare(rep, fast, tol_pct=10.0, tol_overrides={})
    assert not cmp["ok"]
    failed = {r["metric"] for r in cmp["rows"] if not r["ok"]}
    assert "step_ms_p50" in failed
    # tolerance knob: 150% headroom absorbs the 2× delta
    cmp = run_report.compare(rep, fast, tol_pct=150.0, tol_overrides={})
    assert cmp["ok"]
    # per-metric override beats the global knob
    cmp = run_report.compare(
        rep, fast, tol_pct=150.0, tol_overrides={"step_ms_p50": 10.0}
    )
    assert not cmp["ok"]


def test_regression_gate_against_committed_bench_artifact(tmp_path):
    """Satellite 6: the committed BENCH_r05.json is a usable --compare
    reference point, and the gate fails/passes correctly around it —
    exercised end-to-end through the CLI so the gate itself can't rot."""
    bench = json.load(open(os.path.join(REPO, "BENCH_r05.json")))
    ref_ips = float(bench["parsed"]["value"])
    base = run_report.comparable_metrics(bench)
    assert base == {"img_per_sec": ref_ips}

    def run_cli(ips):
        _write_rank(tmp_path, 0, [100.0] * 4)
        rep = run_report.build_report(str(tmp_path))
        rep["img_per_sec"] = ips
        rep_path = tmp_path / "cur.json"
        rep_path.write_text(json.dumps(rep))
        # compare() consumed directly: the CLI wraps exactly this
        return run_report.compare(
            rep, bench, tol_pct=10.0, tol_overrides={}
        )

    assert run_cli(ref_ips * 0.95)["ok"]       # within 10% → PASS
    assert not run_cli(ref_ips * 0.5)["ok"]    # halved throughput → FAIL


def test_run_report_cli_trace_one_command(tmp_path):
    """Acceptance shape: `run_report.py --trace RUN_DIR` writes BOTH the
    merged trace (≥2 rank tracks here) and RUN_REPORT.json."""
    _write_rank(tmp_path, 0, [100.0] * 4)
    _write_rank(tmp_path, 1, [110.0] * 4)
    rc = run_report.main(["--trace", str(tmp_path)])
    assert rc == 0
    trace = json.load(open(tmp_path / "trace.json"))
    pids = {e["pid"] for e in trace["traceEvents"] if e["ph"] == "X"}
    assert pids == {0, 1}
    rep = json.load(open(tmp_path / "RUN_REPORT.json"))
    assert rep["n_ranks"] == 2
    assert rep["step"]["p50_ms"] in (100.0, 110.0)
    assert rep["straggler_skew"] == pytest.approx(1.1)


# --------------------------------------------------- trajectory neutrality
def _tiny_train(tmp_path, enabled: bool):
    import jax

    from distribuuuu_tpu import trainer

    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.DUMMY_INPUT = True
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.TRAIN.BATCH_SIZE = 2
    cfg.TRAIN.IM_SIZE = 32
    cfg.TRAIN.PRINT_FREQ = 4
    cfg.TEST.BATCH_SIZE = 4
    cfg.TEST.IM_SIZE = 32
    cfg.OPTIM.MAX_EPOCH = 1
    cfg.OPTIM.BASE_LR = 0.01
    cfg.RNG_SEED = 0
    cfg.TELEMETRY.ENABLED = enabled
    cfg.OUT_DIR = str(tmp_path / ("on" if enabled else "off"))
    trainer.train_model()
    # the trained params live in the last checkpoint — compare those
    from distribuuuu_tpu.utils import checkpoint as ckpt

    restored = ckpt.load_checkpoint(ckpt.get_checkpoint(0))
    leaves = jax.tree.leaves(restored["params"])
    spans.close_telemetry()
    jsonlog.close_metrics_log()
    return [np.asarray(x) for x in leaves]


@pytest.mark.slow
def test_two_process_run_report_and_trace(tmp_path):
    """The ISSUE 5 acceptance command: a finished 2-process dummy run,
    then ONE command — ``run_report.py --trace out/`` — produces (a) a
    merged Perfetto-loadable trace with ≥ 2 rank tracks and (b)
    RUN_REPORT.json with cross-rank step percentiles, straggler skew,
    data-wait fraction, resilience-event and recompile counts."""
    from tests.test_multiprocess_e2e import _spawn_workers

    out_dir, _outs = _spawn_workers(tmp_path)
    files = export.rank_files(out_dir)
    assert set(files) == {0, 1}  # one sink per rank
    out = subprocess.run(
        [sys.executable, os.path.join("tools", "run_report.py"),
         "--trace", out_dir],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stdout + out.stderr
    trace = json.load(open(os.path.join(out_dir, "trace.json")))
    pids = {e["pid"] for e in trace["traceEvents"] if e["ph"] == "X"}
    assert {0, 1} <= pids  # ≥ 2 rank tracks
    rep = json.load(open(os.path.join(out_dir, "RUN_REPORT.json")))
    assert rep["n_ranks"] == 2
    assert set(rep["per_rank_step"]) == {"0", "1"}
    assert rep["step"]["count"] > 0 and rep["step"]["p50_ms"] > 0
    assert rep["straggler_skew"] >= 1.0
    assert rep["data_wait_frac"] is not None
    assert rep["events"] == {"stall": 0, "data_error": 0, "nonfinite": 0}
    assert rep["recompiles"]["count"] > 0  # both ranks compiled the step
    assert rep["checkpoint"]["saves"] >= 2  # the collective save, per rank
    # every record in every rank file obeys the declared schema
    for path in files.values():
        for rec in _read(path):
            schema.validate_record(rec)


@pytest.mark.slow
def test_trajectory_neutral_end_to_end(tmp_path):
    """The ISSUE 5 hard contract at full train_model scope: telemetry on
    vs off produces bit-identical trained states (1e-7 is the acceptance
    bound; equality is what we actually get — nothing telemetry does
    touches RNG or the compiled step)."""
    on = _tiny_train(tmp_path, enabled=True)
    off = _tiny_train(tmp_path, enabled=False)
    assert os.path.exists(tmp_path / "on" / "telemetry" / "rank00000.jsonl")
    assert not os.path.exists(tmp_path / "off" / "telemetry")
    for a, b in zip(on, off):
        np.testing.assert_allclose(a, b, rtol=0.0, atol=1e-7)


@pytest.mark.slow  # 45s: two full toy train runs; tier-1 budget (ISSUE 18)
def test_trajectory_neutral_step_level(tmp_path):
    """Fast tier-1 half of the neutrality contract: the train_epoch hot
    path with spans enabled produces the identical state as with
    telemetry off (same steps, same metrics, same params)."""
    import jax

    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.parallel import mesh as mesh_lib, sharding
    from distribuuuu_tpu.utils.optim import construct_optimizer

    def run(enabled):
        config.reset_cfg()
        cfg.MODEL.ARCH = "resnet18"
        cfg.MODEL.NUM_CLASSES = 10
        cfg.DEVICE.COMPUTE_DTYPE = "float32"
        cfg.TELEMETRY.ENABLED = enabled
        if enabled:
            spans.setup_telemetry(str(tmp_path / "telemetry"), rank=0)
        mesh = mesh_lib.mesh_from_cfg(cfg)
        model = trainer.build_model_from_cfg()
        state = trainer.create_train_state(model, jax.random.key(0), mesh, 32)
        step = trainer.make_train_step(model, construct_optimizer(), topk=5)
        rng = np.random.default_rng(7)
        for it in range(3):
            hb = {
                "image": rng.standard_normal((16, 32, 32, 3)).astype(np.float32),
                "label": rng.integers(0, 10, size=(16,)).astype(np.int32),
                "mask": np.ones((16,), np.float32),
            }
            t0 = time.perf_counter()
            state, m = step(state, sharding.shard_batch(mesh, hb))
            if enabled:
                trainer._emit_batch_spans(
                    "train", 1, it,
                    {"get0": t0, "get1": t0, "put0": t0, "put1": t0,
                     "step0": t0, "step1": time.perf_counter()},
                )
        spans.close_telemetry()
        return jax.tree.leaves(jax.tree.map(np.asarray, state.params))

    on = run(True)
    off = run(False)
    # spans were really written by the instrumented pass
    recs = _read(tmp_path / "telemetry" / "rank00000.jsonl")
    assert sum(r.get("name") == "step" for r in recs) == 3
    for a, b in zip(on, off):
        np.testing.assert_array_equal(a, b)
