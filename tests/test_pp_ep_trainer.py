"""Trainer-reachable pipeline parallelism (MESH.PIPE) and expert
parallelism (vit_tiny_moe) — VERDICT r1 item 3.

The r1 gap: parallel/pp.py and ops/moe.py were library-level only; the
trainer refused MESH.PIPE>1 and no arch consumed MoE. Now
``train_net.py --cfg config/vit_tiny.yaml MESH.PIPE 4`` trains (GPipe over
the pipe axis, models/vit.PipelinedViT), and ``vit_tiny_moe`` trains
through the normal step with expert tensors sharded over ``model`` and the
switch load-balancing aux (MODEL.MOE.AUX_WEIGHT) added to the loss.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distribuuuu_tpu import models, trainer
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.parallel import mesh as mesh_lib, sharding as sharding_lib
from distribuuuu_tpu.utils.optim import construct_optimizer

pytestmark = pytest.mark.slow  # multi-minute on the 1-core CPU mesh


def _tiny_vit_cfg(pipe=1, model_axis=1, arch="vit_tiny"):
    cfg.MODEL.ARCH = arch
    cfg.MODEL.NUM_CLASSES = 10
    cfg.TRAIN.IM_SIZE = 32
    cfg.TRAIN.BATCH_SIZE = 2  # per chip
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.MESH.PIPE = pipe
    cfg.MESH.MODEL = model_axis
    cfg.MESH.DATA = -1


def _one_step(im=32, seed=0):
    mesh = mesh_lib.mesh_from_cfg(cfg)
    model = trainer.build_model_from_cfg()
    state = trainer.create_train_state(model, jax.random.key(seed), mesh, im)
    optimizer = construct_optimizer()
    step = trainer.make_train_step(model, optimizer, topk=5)
    rng = np.random.default_rng(seed)
    batch = {
        "image": rng.standard_normal((16, im, im, 3)).astype(np.float32),
        "label": rng.integers(0, 10, size=(16,)).astype(np.int32),
        "mask": np.ones((16,), np.float32),
    }
    gbatch = sharding_lib.shard_batch(mesh, batch)
    state, metrics = step(state, gbatch)
    return state, jax.tree.map(float, metrics), model, mesh, gbatch


def test_vit_tiny_trains_with_pipe4():
    """MESH.PIPE=4 (×2 data) trains vit_tiny end-to-end via the trainer's
    normal make_train_step — the r1 refusal is gone."""
    _tiny_vit_cfg(pipe=4)
    # small depth so the CPU-mesh compile stays fast; depth % pipe == 0
    cfg.MESH.MICROBATCH = 4
    trainer.check_trainer_mesh()
    state, metrics, model, mesh, _ = _one_step()
    assert type(model).__name__ == "PipelinedViT"
    assert dict(mesh.shape)["pipe"] == 4
    assert np.isfinite(metrics["loss"])
    # stage params exist and are stacked with leading dim = pipe
    stages = state.params["stages"]
    assert all(leaf.shape[0] == 4 for leaf in jax.tree.leaves(stages))


def test_pipe_matches_dataparallel_forward():
    """The pipelined model's logits equal a plain ViT's when the stacked
    stage params are scattered back into Block_i params (GPipe is
    math-preserving end to end, trainer path included)."""
    _tiny_vit_cfg(pipe=2)
    cfg.MESH.MICROBATCH = 2
    mesh = mesh_lib.mesh_from_cfg(cfg)
    pmodel = trainer.build_model_from_cfg()
    pstate = trainer.create_train_state(pmodel, jax.random.key(0), mesh, 32)

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((8, 32, 32, 3)), jnp.float32)
    plogits = jax.jit(
        lambda p, a: pmodel.apply({"params": p}, a, train=False)
    )(pstate.params, x)

    # rebuild as a plain (non-pipe) ViT with the SAME weights: stage s,
    # local block j  →  Block_{s*k+j}
    dense = models.build_model(
        "vit_tiny", num_classes=10, dtype=jnp.float32
    )
    k = dense.depth // 2
    params = {}
    for name, sub in pstate.params.items():
        if name == "stages":
            for s in range(2):
                for j in range(k):
                    params[f"Block_{s * k + j}"] = jax.tree.map(
                        lambda a: a[s], sub[f"Block_{j}"]
                    )
        else:
            params[name] = sub
    dlogits = jax.jit(
        lambda p, a: dense.apply({"params": p}, a, train=False)
    )(params, x)
    np.testing.assert_allclose(
        np.asarray(plogits), np.asarray(dlogits), atol=2e-5
    )


def test_vit_tiny_moe_trains_with_expert_parallelism():
    """vit_tiny_moe trains through the normal step on a data×model mesh;
    the loss includes the load-balancing aux (λ > 0 changes the loss)."""
    _tiny_vit_cfg(model_axis=2, arch="vit_tiny_moe")
    trainer.check_trainer_mesh()
    state, metrics, model, mesh, gbatch = _one_step()
    assert model.moe_experts == 8
    assert np.isfinite(metrics["loss"])
    # expert tensors are sharded over the model axis (dim 0)
    w_in = None
    for path, leaf in jax.tree_util.tree_flatten_with_path(state.params)[0]:
        if any(getattr(p, "key", None) == "w_in" for p in path):
            w_in = leaf
    assert w_in is not None
    spec = w_in.sharding.spec
    assert spec[0] == "model", f"expert dim not sharded over model: {spec}"


def test_moe_aux_weight_reaches_the_loss():
    _tiny_vit_cfg(arch="vit_tiny_moe")
    losses = {}
    for w in (0.0, 10.0):
        cfg.MODEL.MOE.AUX_WEIGHT = w
        _, metrics, *_ = _one_step(seed=0)
        losses[w] = metrics["loss"]
    assert losses[10.0] > losses[0.0]  # aux ≥ 1 by construction


def test_moe_parallel_matches_dense_reference():
    """EP (model axis 2) and the dense single-axis path produce the same
    logits for the same params — moe_ffn_partial_batched is exact."""
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((4, 32, 32, 3)), jnp.float32)

    _tiny_vit_cfg(model_axis=2, arch="vit_tiny_moe")
    mesh = mesh_lib.mesh_from_cfg(cfg)
    pmodel = trainer.build_model_from_cfg()
    pstate = trainer.create_train_state(pmodel, jax.random.key(0), mesh, 32)
    plogits = jax.jit(
        lambda p, a: pmodel.apply({"params": p}, a, train=False)
    )(pstate.params, x)

    dmodel = models.build_model(
        "vit_tiny_moe", num_classes=10, dtype=jnp.float32
    )
    params_host = jax.tree.map(np.asarray, pstate.params)
    dlogits = dmodel.apply({"params": params_host}, x, train=False)
    np.testing.assert_allclose(
        np.asarray(plogits), np.asarray(dlogits), atol=2e-4
    )


def test_pipe_with_flash_attention():
    """PP × long-sequence attention (VERDICT r2 #7): the flash entry point
    is legal inside pipeline stages (an opaque pallas_call on TPU; the
    blockwise-scan fallback here on the CPU mesh — same exact-softmax
    math), and the pipelined logits match the dense-XLA pipelined model."""
    _tiny_vit_cfg(pipe=2)
    cfg.MESH.MICROBATCH = 2
    cfg.DEVICE.ATTN_IMPL = "flash"
    trainer.check_trainer_mesh()
    state, metrics, model, mesh, _ = _one_step()
    assert type(model).__name__ == "PipelinedViT"
    assert model.attn_impl == "flash"
    assert np.isfinite(metrics["loss"])

    # same stacked params through the xla-attention pipelined model
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.standard_normal((8, 32, 32, 3)), jnp.float32)
    flogits = jax.jit(
        lambda p, a: model.apply({"params": p}, a, train=False)
    )(state.params, x)
    cfg.DEVICE.ATTN_IMPL = "xla"
    xmodel = trainer.build_model_from_cfg()
    xlogits = jax.jit(
        lambda p, a: xmodel.apply({"params": p}, a, train=False)
    )(state.params, x)
    np.testing.assert_allclose(
        np.asarray(flogits), np.asarray(xlogits), atol=2e-4
    )


def test_vit_tiny_moe_trains_with_dispatch():
    """MODEL.MOE.IMPL=dispatch routes MoeMlp through the all_to_all switch
    path in the real trainer step; the dropped-assignment fraction surfaces
    as the ``moe_dropped`` metric (0 at ample capacity)."""
    _tiny_vit_cfg(model_axis=2, arch="vit_tiny_moe")
    cfg.MODEL.MOE.IMPL = "dispatch"
    cfg.MODEL.MOE.CAPACITY_FACTOR = float(cfg.MODEL.MOE.NUM_EXPERTS)
    trainer.check_trainer_mesh()
    state, metrics, model, mesh, _ = _one_step()
    assert model.moe_impl == "dispatch"
    assert np.isfinite(metrics["loss"])
    assert metrics["moe_dropped"] == 0.0


def test_dispatch_trainer_drops_under_tight_capacity():
    _tiny_vit_cfg(model_axis=2, arch="vit_tiny_moe")
    cfg.MODEL.MOE.IMPL = "dispatch"
    cfg.MODEL.MOE.CAPACITY_FACTOR = 0.25
    _, metrics, *_ = _one_step()
    assert np.isfinite(metrics["loss"])
    assert 0.0 < metrics["moe_dropped"] < 1.0


def test_dispatch_logits_match_partial_at_ample_capacity():
    """Same params, ample capacity: the dispatch model's logits equal the
    partial (exact) model's — the switch path is exact when nothing drops."""
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((8, 32, 32, 3)), jnp.float32)

    _tiny_vit_cfg(model_axis=2, arch="vit_tiny_moe")
    mesh = mesh_lib.mesh_from_cfg(cfg)
    pmodel = trainer.build_model_from_cfg()  # partial (default)
    pstate = trainer.create_train_state(pmodel, jax.random.key(0), mesh, 32)
    plogits = jax.jit(
        lambda p, a: pmodel.apply({"params": p}, a, train=False)
    )(pstate.params, x)

    cfg.MODEL.MOE.IMPL = "dispatch"
    cfg.MODEL.MOE.CAPACITY_FACTOR = float(cfg.MODEL.MOE.NUM_EXPERTS)
    dmodel = trainer.build_model_from_cfg()
    dlogits = jax.jit(
        lambda p, a: dmodel.apply({"params": p}, a, train=False)
    )(pstate.params, x)
    np.testing.assert_allclose(
        np.asarray(plogits), np.asarray(dlogits), atol=2e-4
    )


def test_pipe_refusals():
    _tiny_vit_cfg(pipe=4, arch="resnet18")
    with pytest.raises(ValueError, match="uniform-stage"):
        trainer.check_trainer_mesh()
    # uneven expert placement across stages refused at model build:
    # depth 12 / pipe 4 = 3 blocks per stage, not divisible by EVERY 2
    _tiny_vit_cfg(pipe=4, arch="vit_tiny_moe")
    trainer.check_trainer_mesh()
    with pytest.raises(ValueError, match="blocks-per-stage"):
        trainer.build_model_from_cfg()._stage_module()


def test_vit_tiny_moe_trains_with_pipeline():
    """PP×EP (r3): vit_tiny_moe trains through the normal step on a
    data×model×pipe mesh — MoE blocks run the inline expert-partials body
    on the bound model axis inside the pipeline's shard_map."""
    _tiny_vit_cfg(pipe=2, model_axis=2, arch="vit_tiny_moe")
    cfg.MESH.MICROBATCH = 2
    trainer.check_trainer_mesh()
    state, metrics, model, mesh, _ = _one_step()
    assert type(model).__name__ == "PipelinedViT"
    assert dict(mesh.shape) == {"data": 2, "model": 2, "seq": 1, "pipe": 2}
    assert np.isfinite(metrics["loss"])
    # expert tensors live in the stacked stages: [pipe, E, ...]
    w_in = None
    for path, leaf in jax.tree_util.tree_flatten_with_path(state.params)[0]:
        if any(getattr(p, "key", None) == "w_in" for p in path):
            w_in = leaf
    assert w_in is not None and w_in.shape[:2] == (2, 8)


def test_pipelined_moe_matches_flat_reference():
    """Pipelined MoE logits equal the flat vit_tiny_moe's (reference MoE
    path) when the stacked stage params are scattered into Block_i —
    placement coincides because blocks-per-stage (6) % EVERY (2) == 0."""
    _tiny_vit_cfg(pipe=2, model_axis=2, arch="vit_tiny_moe")
    cfg.MESH.MICROBATCH = 2
    mesh = mesh_lib.mesh_from_cfg(cfg)
    pmodel = trainer.build_model_from_cfg()
    pstate = trainer.create_train_state(pmodel, jax.random.key(0), mesh, 32)

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((8, 32, 32, 3)), jnp.float32)
    plogits = jax.jit(
        lambda p, a: pmodel.apply({"params": p}, a, train=False)
    )(pstate.params, x)

    dense = models.build_model(
        "vit_tiny_moe", num_classes=10, dtype=jnp.float32
    )
    k = dense.depth // 2
    params = {}
    for name, sub in pstate.params.items():
        if name == "stages":
            for s in range(2):
                for j in range(k):
                    params[f"Block_{s * k + j}"] = jax.tree.map(
                        lambda a: np.asarray(a[s]), sub[f"Block_{j}"]
                    )
        else:
            params[name] = jax.tree.map(np.asarray, sub)
    dlogits = dense.apply({"params": params}, x, train=False)
    np.testing.assert_allclose(
        np.asarray(plogits), np.asarray(dlogits), atol=2e-4
    )


def _scatter_stages_to_flat(pstate_params, depth, pipe):
    """Stacked stage params → flat ViT Block_i params (host arrays)."""
    k = depth // pipe
    params = {}
    for name, sub in pstate_params.items():
        if name == "stages":
            for s in range(pipe):
                for j in range(k):
                    params[f"Block_{s * k + j}"] = jax.tree.map(
                        lambda a: np.asarray(a[s]), sub[f"Block_{j}"]
                    )
        else:
            params[name] = jax.tree.map(np.asarray, sub)
    return params


def test_pp_moe_aux_matches_flat_reference():
    """VERDICT r3 #2: the balancing aux collected through the pipeline's
    stage-aux channel (per-microbatch (f, p) accumulation → full-batch
    reconstruction) equals the flat model's full-batch aux to float
    tolerance — not just 'some aux exists'."""
    _tiny_vit_cfg(pipe=2, model_axis=2, arch="vit_tiny_moe")
    cfg.MESH.MICROBATCH = 2
    mesh = mesh_lib.mesh_from_cfg(cfg)
    pmodel = trainer.build_model_from_cfg()
    pstate = trainer.create_train_state(pmodel, jax.random.key(0), mesh, 32)

    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.standard_normal((8, 32, 32, 3)), jnp.float32)
    _, pmut = jax.jit(
        lambda p, a: pmodel.apply(
            {"params": p}, a, train=True, mutable=["intermediates"]
        )
    )(pstate.params, x)
    paux = jax.tree.leaves(pmut["intermediates"])
    assert len(paux) == 1  # ONE scalar: the mean over all MoE blocks

    dense = models.build_model(
        "vit_tiny_moe", num_classes=10, dtype=jnp.float32
    )
    params = _scatter_stages_to_flat(pstate.params, dense.depth, 2)
    _, dmut = dense.apply(
        {"params": params}, x, train=True, mutable=["intermediates"]
    )
    daux = jax.tree.leaves(dmut["intermediates"])
    assert len(daux) == dense.depth // dense.moe_every  # one per MoE block
    np.testing.assert_allclose(
        float(paux[0]), float(np.mean([float(a) for a in daux])), rtol=1e-5
    )


def test_pp_moe_aux_weight_reaches_the_loss():
    """MODEL.MOE.AUX_WEIGHT moves the PIPELINED loss (r4 — it contributed
    nothing under PP in r3)."""
    losses = {}
    for w in (0.0, 10.0):
        _tiny_vit_cfg(pipe=2, model_axis=2, arch="vit_tiny_moe")
        cfg.MESH.MICROBATCH = 2
        cfg.MODEL.MOE.AUX_WEIGHT = w
        _, metrics, model, *_ = _one_step(seed=0)
        assert type(model).__name__ == "PipelinedViT"
        losses[w] = metrics["loss"]
    assert losses[10.0] > losses[0.0]  # aux ≥ 1 by construction


def test_vit_tiny_moe_trains_with_pipeline_dispatch():
    """PP×EP-dispatch (VERDICT r3 #3): the switch all_to_all strategy runs
    inline inside pipeline stages on the bound model axis; the dropped
    fraction rides the stage-aux channel to the ``moe_dropped`` metric."""
    _tiny_vit_cfg(pipe=2, model_axis=2, arch="vit_tiny_moe")
    cfg.MESH.MICROBATCH = 2
    cfg.MODEL.MOE.IMPL = "dispatch"
    cfg.MODEL.MOE.CAPACITY_FACTOR = float(cfg.MODEL.MOE.NUM_EXPERTS)
    trainer.check_trainer_mesh()
    state, metrics, model, mesh, _ = _one_step()
    assert type(model).__name__ == "PipelinedViT"
    assert model.moe_impl == "dispatch"
    assert np.isfinite(metrics["loss"])
    assert metrics["moe_dropped"] == 0.0  # ample capacity drops nothing


def test_pp_dispatch_logits_match_pp_partial():
    """Ample capacity: the PP-dispatch model's logits equal the PP-partial
    (exact) model's on the same stacked params."""
    rng = np.random.default_rng(13)
    x = jnp.asarray(rng.standard_normal((8, 32, 32, 3)), jnp.float32)

    _tiny_vit_cfg(pipe=2, model_axis=2, arch="vit_tiny_moe")
    cfg.MESH.MICROBATCH = 2
    mesh = mesh_lib.mesh_from_cfg(cfg)
    pmodel = trainer.build_model_from_cfg()  # partial (default)
    pstate = trainer.create_train_state(pmodel, jax.random.key(0), mesh, 32)
    plogits = jax.jit(
        lambda p, a: pmodel.apply({"params": p}, a, train=False)
    )(pstate.params, x)

    cfg.MODEL.MOE.IMPL = "dispatch"
    cfg.MODEL.MOE.CAPACITY_FACTOR = float(cfg.MODEL.MOE.NUM_EXPERTS)
    dmodel = trainer.build_model_from_cfg()
    dlogits = jax.jit(
        lambda p, a: dmodel.apply({"params": p}, a, train=False)
    )(pstate.params, x)
    np.testing.assert_allclose(
        np.asarray(plogits), np.asarray(dlogits), atol=2e-4
    )
