"""Resilience layer (ISSUE 3): verified checkpoints, elastic resume,
failure supervision, fault injection.

Fast tier (default): manifest commit/verify, corrupt-checkpoint walk-back
with quarantine, the actionable load error, SIGTERM handler chaining, the
in-graph non-finite guard, loader retry/skip, the heartbeat watchdog, and
topology classification — the pure recovery logic, on tiny trees so a
regression in any path fails ``pytest -m 'not slow'``.

Slow tier: elastic cross-topology resume proven on real state (save at
dp=4 → resume at dp=2 AND dp=8, ZeRO-1 included, trajectory-equivalent to
the uninterrupted run within the lockstep tolerance of tests/test_zero.py)
and the NaN-injection policies through a real compiled train step.
"""

import json
import os
import signal

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.resilience import manifest, supervisor
from distribuuuu_tpu.utils import checkpoint as ckpt, faults


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _tiny_tree(seed: float = 0.0):
    return {
        "params": {"w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3) + seed},
        "batch_stats": {"m": jnp.ones((3,), jnp.float32)},
        "opt_state": {"mu": jnp.full((2, 3), 0.5 + seed, jnp.float32)},
    }


def _truncate_largest(path: str):
    largest, size = None, -1
    for dirpath, _, names in os.walk(path):
        for name in names:
            if name == manifest.MANIFEST_NAME:
                continue
            full = os.path.join(dirpath, name)
            if os.path.getsize(full) > size:
                largest, size = full, os.path.getsize(full)
    assert largest is not None and size > 0
    with open(largest, "r+b") as f:
        f.truncate(size // 2)
    return largest


# ------------------------------------------------------- manifest commit


def test_save_commits_manifest_and_verifies(tmp_path):
    cfg.OUT_DIR = str(tmp_path)
    ckpt.save_checkpoint(_tiny_tree(), epoch=0, best_acc1=1.0, is_best=True)
    path = ckpt.get_checkpoint(0)
    man = manifest.read_manifest(path)
    assert man is not None and man["kind"] == "full" and man["epoch"] == 0
    # tree spec covers the payload leaves; files carry size+sha256
    assert any("params" in k for k in man["tree"])
    assert man["files"] and all(
        "sha256" in v and v["size"] > 0 for v in man["files"].values()
    )
    ok, reason = manifest.verify_checkpoint(path)
    assert ok, reason
    # the weights-only best checkpoint is committed too
    ok, reason = manifest.verify_checkpoint(ckpt.get_best_checkpoint())
    assert ok, reason


def test_verify_detects_truncation_and_missing_manifest(tmp_path):
    cfg.OUT_DIR = str(tmp_path)
    ckpt.save_checkpoint(_tiny_tree(), epoch=0, best_acc1=0.0, is_best=False)
    path = ckpt.get_checkpoint(0)
    _truncate_largest(path)
    ok, reason = manifest.verify_checkpoint(path)
    assert not ok and ("truncated" in reason or "digest" in reason), reason
    # no manifest ⇒ the save never committed ⇒ invalid by definition
    os.unlink(manifest.manifest_path(path))
    ok, reason = manifest.verify_checkpoint(path)
    assert not ok and "manifest" in reason, reason


# ---------------------------------------------- walk-back + quarantine


def test_walkback_quarantines_and_lands_on_previous_epoch(tmp_path):
    """The ISSUE's headline regression: a half-written newest ckpt_ep_* no
    longer kills the resume — it is quarantined to *.corrupt and the scan
    walks back to the newest intact save."""
    cfg.OUT_DIR = str(tmp_path)
    ckpt.save_checkpoint(_tiny_tree(0.0), epoch=0, best_acc1=0.0, is_best=False)
    ckpt.save_checkpoint(_tiny_tree(9.0), epoch=1, best_acc1=0.0, is_best=False)
    _truncate_largest(ckpt.get_checkpoint(1))

    found = ckpt.find_last_valid_checkpoint()
    assert found.endswith("ckpt_ep_000")
    names = sorted(os.listdir(ckpt.get_checkpoint_dir()))
    assert "ckpt_ep_001.corrupt" in names and "ckpt_ep_001" not in names, names
    # the survivor restores cleanly with epoch-0 values
    restored = ckpt.load_checkpoint(found)
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["w"]),
        np.arange(6, dtype=np.float32).reshape(2, 3),
    )


def test_partial_save_without_manifest_is_walked_past(tmp_path):
    """Crash-before-commit: a dir with payload bytes but no manifest (the
    window the atomic commit protocol closes) is treated as partial."""
    cfg.OUT_DIR = str(tmp_path)
    ckpt.save_checkpoint(_tiny_tree(), epoch=0, best_acc1=0.0, is_best=False)
    partial = ckpt.get_checkpoint(1)
    os.makedirs(partial)
    with open(os.path.join(partial, "junk"), "wb") as f:
        f.write(b"half-written")
    assert ckpt.get_last_checkpoint() == partial  # the raw scan would pick it
    assert ckpt.find_last_valid_checkpoint().endswith("ckpt_ep_000")
    assert "ckpt_ep_001.corrupt" in os.listdir(ckpt.get_checkpoint_dir())


def test_all_corrupt_raises_no_valid(tmp_path):
    cfg.OUT_DIR = str(tmp_path)
    ckpt.save_checkpoint(_tiny_tree(), epoch=0, best_acc1=0.0, is_best=False)
    _truncate_largest(ckpt.get_checkpoint(0))
    with pytest.raises(ckpt.NoValidCheckpointError, match="none verified"):
        ckpt.find_last_valid_checkpoint()


def test_corrupt_preempt_walks_back_to_epoch_checkpoint(tmp_path):
    """Preference ordering survives verification: a corrupt preempt_ep_1
    (which outranks ckpt_ep_000) is quarantined, not selected forever."""
    from distribuuuu_tpu.utils.checkpoint import save_preempt_checkpoint

    cfg.OUT_DIR = str(tmp_path)
    ckpt.save_checkpoint(_tiny_tree(), epoch=0, best_acc1=0.0, is_best=False)
    save_preempt_checkpoint(_tiny_tree(1.0), epoch=1, best_acc1=0.0)
    assert ckpt.find_last_valid_checkpoint().endswith("preempt_ep_001")
    _truncate_largest(os.path.join(ckpt.get_checkpoint_dir(), "preempt_ep_001"))
    assert ckpt.find_last_valid_checkpoint().endswith("ckpt_ep_000")


# -------------------------------------------------- actionable load error


def test_load_checkpoint_failure_is_actionable(tmp_path):
    """Satellite 2: a broken orbax restore names the path, the quarantine
    action, and the resume-from-previous command — no raw tensorstore
    traceback as the only signal."""
    cfg.OUT_DIR = str(tmp_path)
    ckpt.save_checkpoint(_tiny_tree(), epoch=3, best_acc1=0.0, is_best=False)
    path = ckpt.get_checkpoint(3)
    _truncate_largest(path)
    with pytest.raises(ckpt.CheckpointLoadError) as ei:
        ckpt.load_checkpoint(path)
    msg = str(ei.value)
    assert "ckpt_ep_003" in msg
    assert "quarantined to" in msg and ".corrupt" in msg
    assert "TRAIN.AUTO_RESUME" in msg and "MODEL.WEIGHTS" in msg
    assert not os.path.exists(path)  # really moved aside


def test_load_checkpoint_outside_run_dir_not_quarantined(tmp_path):
    """A user-supplied path (MODEL.WEIGHTS) is never renamed."""
    cfg.OUT_DIR = str(tmp_path)
    alien = tmp_path / "my_weights"
    alien.mkdir()
    (alien / "junk").write_bytes(b"not a checkpoint")
    with pytest.raises(ckpt.CheckpointLoadError, match="no quarantine"):
        ckpt.load_checkpoint(str(alien))
    assert alien.exists()


# ------------------------------------------------- topology classification


def test_topology_classification(tmp_path):
    cfg.OUT_DIR = str(tmp_path)
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    ckpt.save_checkpoint(_tiny_tree(), epoch=0, best_acc1=0.0, is_best=False)
    man = manifest.read_manifest(ckpt.get_checkpoint(0))

    live_spec = manifest.tree_spec(
        {k: _tiny_tree()[k] for k in ("params", "batch_stats")}
    )
    kind, _ = manifest.classify_topology(man, live_spec)
    assert kind == "exact"

    # a different world (the elastic case) ⇒ reshardable, named diff
    man2 = json.loads(json.dumps(man))
    man2["topology"]["devices"] = 64
    man2["topology"]["zero"] = 1
    kind, detail = manifest.classify_topology(man2, live_spec)
    assert kind == "reshardable" and "devices 64" in detail, detail

    # arch identity changed ⇒ incompatible via fingerprint
    cfg.MODEL.NUM_CLASSES = 1000
    kind, detail = manifest.classify_topology(man, live_spec)
    assert kind == "incompatible" and "fingerprint" in detail
    cfg.MODEL.NUM_CLASSES = 10

    # param shape changed ⇒ incompatible via tree spec
    bad_spec = dict(live_spec)
    key = next(k for k in bad_spec if "w" in k)
    bad_spec[key] = {"shape": [4, 3], "dtype": "float32"}
    kind, detail = manifest.classify_topology(man, bad_spec)
    assert kind == "incompatible" and "shape" in detail


# ---------------------------------------------- SIGTERM handler chaining


def test_preempt_install_chains_prior_sigterm_handler():
    """Satellite 1: preempt.install no longer clobbers a previously
    installed SIGTERM handler (the serve drain registers one too) — both
    flags trip on one signal, in either install order."""
    from distribuuuu_tpu.serve import admission
    from distribuuuu_tpu.utils import preempt

    orig = signal.getsignal(signal.SIGTERM)
    try:
        for first, second in (
            (admission.install_drain, preempt.install),
            (preempt.install, admission.install_drain),
        ):
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            preempt.reset()
            admission.reset_drain()
            first()
            second()
            os.kill(os.getpid(), signal.SIGTERM)
            assert preempt.requested_local(), (first, second)
            assert admission.drain_requested(), (first, second)
        # idempotent re-install must not chain to itself (no recursion)
        preempt.reset()
        preempt.install()
        preempt.install()
        os.kill(os.getpid(), signal.SIGTERM)
        assert preempt.requested_local()
    finally:
        signal.signal(signal.SIGTERM, orig)
        preempt.reset()
        admission.reset_drain()


# ------------------------------------------------- in-graph nonfinite guard


def _guard_fixture():
    from distribuuuu_tpu.trainer import TrainState

    old = TrainState(
        params={"w": jnp.ones((2, 2))},
        batch_stats={"m": jnp.zeros((2,))},
        opt_state={"mu": jnp.full((2, 2), 0.5)},
        step=jnp.int32(7),
        key=jax.random.key(0),
    )
    new = TrainState(
        params={"w": jnp.full((2, 2), 2.0)},
        batch_stats={"m": jnp.ones((2,))},
        opt_state={"mu": jnp.full((2, 2), 0.9)},
        step=old.step + 1,
        key=old.key,  # the step never touches the base key (same object)
    )
    return old, new


@pytest.mark.parametrize("policy", ["raise", "skip", "rollback"])
def test_guard_nonfinite_annotates_every_policy(policy):
    old, new = _guard_fixture()
    guarded, metrics = jax.jit(
        lambda o, n, loss: supervisor.guard_nonfinite(
            o, n, {"loss": loss}, policy
        )
    )(old, new, jnp.float32(1.25))
    assert float(metrics["nonfinite"]) == 0.0
    np.testing.assert_array_equal(np.asarray(guarded.params["w"]), 2.0)


def test_guard_nonfinite_skip_reverts_state_but_advances_step():
    old, new = _guard_fixture()
    guarded, metrics = jax.jit(
        lambda o, n, loss: supervisor.guard_nonfinite(
            o, n, {"loss": loss}, "skip"
        )
    )(old, new, jnp.float32(np.nan))
    assert float(metrics["nonfinite"]) == 1.0
    # poisoned update discarded wholesale...
    np.testing.assert_array_equal(np.asarray(guarded.params["w"]), 1.0)
    np.testing.assert_array_equal(np.asarray(guarded.opt_state["mu"]), 0.5)
    np.testing.assert_array_equal(np.asarray(guarded.batch_stats["m"]), 0.0)
    # ...but the step cursor advances (RNG folding moves on)
    assert int(guarded.step) == 8


def test_guard_nonfinite_raise_policy_keeps_state():
    """'raise' detects at the host; the graph must not silently skip."""
    old, new = _guard_fixture()
    guarded, metrics = jax.jit(
        lambda o, n, loss: supervisor.guard_nonfinite(
            o, n, {"loss": loss}, "raise"
        )
    )(old, new, jnp.float32(np.inf))
    assert float(metrics["nonfinite"]) == 1.0
    np.testing.assert_array_equal(np.asarray(guarded.params["w"]), 2.0)


def test_nonfinite_monitor_policies():
    mon = supervisor.NonFiniteMonitor("skip", epoch=0)
    assert mon.observe(1.0, 0.0, batch=3) is False
    assert mon.observe(float("nan"), 1.0, batch=4) is True
    assert mon.skipped == 1
    mon = supervisor.NonFiniteMonitor("raise", epoch=2)
    with pytest.raises(supervisor.NonFiniteLossError, match="epoch 3"):
        mon.observe(float("nan"), 1.0, batch=5)
    with pytest.raises(ValueError, match="TRAIN.NONFINITE"):
        supervisor.NonFiniteMonitor("bogus", epoch=0)


def test_nonfinite_policy_validated_in_config_checks():
    from distribuuuu_tpu import trainer

    config.reset_cfg()
    cfg.TRAIN.NONFINITE = "explode"
    with pytest.raises(ValueError, match="TRAIN.NONFINITE"):
        trainer.check_trainer_mesh()


# ------------------------------------------------------ loader resilience


def _tiny_loader(batch_size=4, length=16):
    from distribuuuu_tpu.data.dummy import DummyDataset
    from distribuuuu_tpu.data.loader import Loader

    return Loader(
        DummyDataset(length=length, size=8),
        batch_size=batch_size, shuffle=False, drop_last=True, workers=1,
    )


def test_loader_retry_recovers_transient_decode_error():
    """FAULTS 'once' mode: the first touch of sample 3 raises; the loader's
    retry-with-backoff succeeds — the epoch completes with real data."""
    cfg.DATA.RETRY_BACKOFF_S = 0.001
    cfg.FAULTS.ENABLED = True
    cfg.FAULTS.DECODE_ERROR_IDX = 3
    cfg.FAULTS.DECODE_ERROR_MODE = "once"
    batches = list(_tiny_loader())
    assert len(batches) == 4
    assert all(b["image"].shape == (4, 8, 8, 3) for b in batches)
    # retry delivered the REAL sample 3, not a substitute
    expected = np.random.default_rng(3).standard_normal(
        (8, 8, 3), dtype=np.float32
    )
    np.testing.assert_array_equal(batches[0]["image"][3], expected)


def test_loader_skips_and_substitutes_persistently_corrupt_sample():
    """'always' mode: sample 5 never decodes; it is replaced by a good
    sample from the same batch (shape-stable for jit) and the epoch
    completes instead of aborting."""
    cfg.DATA.RETRIES = 1
    cfg.DATA.RETRY_BACKOFF_S = 0.001
    cfg.FAULTS.ENABLED = True
    cfg.FAULTS.DECODE_ERROR_IDX = 5
    cfg.FAULTS.DECODE_ERROR_MODE = "always"
    batches = list(_tiny_loader())
    assert len(batches) == 4
    # slot 5 (batch 1, position 1) now holds batch 1's first good sample
    expected = np.random.default_rng(4).standard_normal(
        (8, 8, 3), dtype=np.float32
    )
    np.testing.assert_array_equal(batches[1]["image"][1], expected)


def test_loader_fail_stop_when_skip_disabled():
    cfg.DATA.RETRIES = 0
    cfg.DATA.SKIP_CORRUPT = False
    cfg.FAULTS.ENABLED = True
    cfg.FAULTS.DECODE_ERROR_IDX = 5
    cfg.FAULTS.DECODE_ERROR_MODE = "always"
    with pytest.raises(RuntimeError, match="fail-stop"):
        list(_tiny_loader())


# ------------------------------------------------------ heartbeat watchdog


def test_heartbeat_flags_stall_and_quiet_when_beaten():
    import time

    hb = supervisor.Heartbeat(0.05)
    try:
        time.sleep(0.3)
        assert hb.stall_count >= 1
        stalled = hb.stall_count
        # one stall is flagged once, not once per poll
        time.sleep(0.15)
        assert hb.stall_count == stalled
        hb.beat("recovered")
        time.sleep(0.02)
        assert hb.stall_count == stalled
    finally:
        hb.stop()

    hb = supervisor.Heartbeat(0.2)
    try:
        for _ in range(10):
            hb.beat("busy")
            time.sleep(0.02)
        assert hb.stall_count == 0
    finally:
        hb.stop()

    hb = supervisor.Heartbeat(0.0)  # disabled: no thread, no-ops
    hb.beat()
    hb.stop()
    assert hb.stall_count == 0


# ----------------------------------------------- elastic resume (slow tier)


BATCH = 16
LOCKSTEP_ATOL = (1e-5, 2e-2)  # step-0 exactness, step-1 drift (test_zero.py)


def _stream_batch(step: int, n: int = BATCH):
    rng = np.random.default_rng(7_000 + step)
    images = rng.standard_normal((n, 32, 32, 3)).astype(np.float32)
    labels = (
        (images.mean(axis=(1, 2, 3)) * 40.0).astype(np.int64) % 10
    ).astype(np.int32)
    images += labels[:, None, None, None] * 0.1
    return {"image": images, "label": labels, "mask": np.ones((n,), np.float32)}


def _elastic_setup(tmp_path, dp: int, zero_stage: int):
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.parallel import mesh as mesh_lib
    from distribuuuu_tpu.utils.optim import construct_optimizer

    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.BN_GROUP = 8
    cfg.OPTIM.BASE_LR = 0.05
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.MESH.ZERO = zero_stage
    cfg.OUT_DIR = str(tmp_path)
    mesh = mesh_lib.build_mesh(data=dp, devices=jax.devices()[:dp])
    model = trainer.build_model_from_cfg()
    layout = trainer._state_layout(model, mesh, 32) if zero_stage else None
    state = trainer.create_train_state(
        model, jax.random.key(0), mesh, 32, layout=layout
    )
    step = trainer.make_train_step(
        model, construct_optimizer(), topk=5, layout=layout
    )
    return mesh, model, state, step


def _run_steps(mesh, state, step, first: int, last: int):
    from distribuuuu_tpu.parallel import sharding as sharding_lib

    losses = []
    for it in range(first, last):
        batch = sharding_lib.shard_batch(mesh, _stream_batch(it))
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    return state, losses


@pytest.mark.slow
@pytest.mark.parametrize("zero_stage", [0, 1])
def test_elastic_resume_dp4_to_dp2_and_dp8(tmp_path, zero_stage):
    """The acceptance drill: save at dp=4, resume at dp=2 AND dp=8 (ZeRO-1
    variant reassembles sharded optimizer state through pack_opt_state),
    each reproducing the uninterrupted dp=4 trajectory within the lockstep
    tolerance — elastic resume is trajectory-equivalent, not merely
    crash-free."""
    from distribuuuu_tpu import trainer

    # uninterrupted reference: 4 steps, then 2 more, all at dp=4
    mesh4, _, state, step = _elastic_setup(tmp_path / "ref", 4, zero_stage)
    state, _ = _run_steps(mesh4, state, step, 0, 4)
    _, base_tail = _run_steps(mesh4, state, step, 4, 6)

    # interrupted run: identical 4 steps at dp=4, checkpointed
    mesh4b, _, state_b, step_b = _elastic_setup(tmp_path / "run", 4, zero_stage)
    state_b, _ = _run_steps(mesh4b, state_b, step_b, 0, 4)
    ckpt.save_checkpoint(trainer._state_tree(state_b), 0, 0.0, False)
    man = manifest.read_manifest(ckpt.get_checkpoint(0))
    assert man["topology"]["mesh"].get("data") == 4

    for dp in (2, 8):
        mesh_n, _, fresh, step_n = _elastic_setup(tmp_path / "run", dp, zero_stage)
        resumed, start_epoch, _, _, _ = trainer._resume(fresh, mesh_n)
        assert start_epoch == 1 and int(resumed.step) == 4
        # no silent weights-only fallback: momenta must equal the saved ones
        saved_mom = [
            np.asarray(x) for x in jax.tree.leaves(state_b.opt_state)
            if hasattr(x, "ndim") and x.ndim >= 2
        ]
        got_mom = [
            np.asarray(x) for x in jax.tree.leaves(resumed.opt_state)
            if hasattr(x, "ndim") and x.ndim >= 2
        ]
        assert any(np.abs(m).max() > 0 for m in saved_mom)
        for a, b in zip(saved_mom, got_mom):
            np.testing.assert_array_equal(a, b)
        _, tail = _run_steps(mesh_n, resumed, step_n, 4, 6)
        assert np.isfinite(tail).all(), (dp, tail)
        np.testing.assert_allclose(
            tail[0], base_tail[0], rtol=0, atol=LOCKSTEP_ATOL[0],
            err_msg=f"dp={dp} zero={zero_stage} first resumed step",
        )
        np.testing.assert_allclose(
            tail[1], base_tail[1], rtol=0, atol=LOCKSTEP_ATOL[1],
            err_msg=f"dp={dp} zero={zero_stage} second resumed step",
        )


@pytest.mark.slow
def test_elastic_resume_refuses_incompatible_model(tmp_path):
    """The manifest topology check distinguishes re-shardable from
    incompatible: a NUM_CLASSES change refuses with the reason instead of
    a shape error deep in device_put."""
    from distribuuuu_tpu import trainer

    mesh, _, state, step = _elastic_setup(tmp_path, 4, 0)
    state, _ = _run_steps(mesh, state, step, 0, 1)
    ckpt.save_checkpoint(trainer._state_tree(state), 0, 0.0, False)

    cfg.MODEL.NUM_CLASSES = 37
    model2 = trainer.build_model_from_cfg()
    fresh = trainer.create_train_state(model2, jax.random.key(1), mesh, 32)
    with pytest.raises(ckpt.CheckpointError, match="cannot feed"):
        trainer._resume(fresh, mesh)


# -------------------------------------- NaN injection e2e (slow tier)


@pytest.mark.slow
def test_nan_injection_skip_policy_through_real_step(tmp_path):
    """FAULTS.NAN_STEP=1 + TRAIN.NONFINITE=skip through a real compiled
    step: step 1's poisoned update is discarded in-graph (params equal the
    post-step-0 params), the flag reads 1.0 exactly there, and training
    continues finite afterward."""
    mesh, model, state, _ = _elastic_setup(tmp_path, 8, 0)
    # rebuild the step with the injection + skip policy compiled in
    cfg.TRAIN.NONFINITE = "skip"
    cfg.FAULTS.ENABLED = True
    cfg.FAULTS.NAN_STEP = 1
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.utils.optim import construct_optimizer

    step = trainer.make_train_step(model, construct_optimizer(), topk=5)
    from distribuuuu_tpu.parallel import sharding as sharding_lib

    state, m0 = step(state, sharding_lib.shard_batch(mesh, _stream_batch(0)))
    assert float(m0["nonfinite"]) == 0.0
    w_after0 = np.asarray(jax.tree.leaves(state.params)[0])

    state, m1 = step(state, sharding_lib.shard_batch(mesh, _stream_batch(1)))
    assert float(m1["nonfinite"]) == 1.0
    assert not np.isfinite(float(m1["loss"]))
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(state.params)[0]), w_after0
    )
    assert int(state.step) == 2  # the cursor advanced past the bad step

    state, m2 = step(state, sharding_lib.shard_batch(mesh, _stream_batch(2)))
    assert float(m2["nonfinite"]) == 0.0
    assert np.isfinite(float(m2["loss"]))
    assert np.isfinite(np.asarray(jax.tree.leaves(state.params)[0])).all()


@pytest.mark.slow
def test_nan_rollback_policy_reloads_checkpoint(tmp_path):
    """TRAIN.NONFINITE=rollback through train_model: a deterministic NaN in
    epoch 1 rolls the run back to ckpt_ep_000 (logged), re-trips, and
    surfaces once TRAIN.MAX_ROLLBACKS is spent — while a clean rerun (the
    transient passed) completes from the same checkpoint."""
    import logging

    from distribuuuu_tpu import trainer

    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.DUMMY_INPUT = True
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.TRAIN.BATCH_SIZE = 2
    cfg.TRAIN.IM_SIZE = 32
    cfg.TRAIN.PRINT_FREQ = 2
    cfg.TEST.BATCH_SIZE = 4
    cfg.TEST.IM_SIZE = 32
    cfg.OPTIM.MAX_EPOCH = 2
    cfg.OUT_DIR = str(tmp_path)
    cfg.RNG_SEED = 0
    cfg.TRAIN.NONFINITE = "rollback"
    cfg.TRAIN.MAX_ROLLBACKS = 1
    cfg.FAULTS.ENABLED = True
    cfg.FAULTS.NAN_STEP = 11  # inside epoch 1 (8 batches/epoch at this size)

    # the package logger has propagate=False, so capture with our own
    # handler rather than caplog
    messages = []
    handler = logging.Handler()
    handler.emit = lambda record: messages.append(record.getMessage())
    logging.getLogger("distribuuuu_tpu").addHandler(handler)
    try:
        with pytest.raises(supervisor.NonFiniteLossError):
            trainer.train_model()
    finally:
        logging.getLogger("distribuuuu_tpu").removeHandler(handler)
    assert any("rolling back" in m for m in messages), messages[-5:]
    # epoch 0's checkpoint is intact; the clean rerun resumes and finishes
    cfg.FAULTS.ENABLED = False
    cfg.FAULTS.NAN_STEP = -1
    best = trainer.train_model()
    assert np.isfinite(best)
    names = os.listdir(ckpt.get_checkpoint_dir())
    assert "ckpt_ep_001" in names, names
