"""Length-aware serving (ISSUE 19): the long-prompt admission
reservation (one burst of chunked 4k prefills cannot starve the decode
batch), the verbatim queue_full frame shape, and the router's per-class
routing stats that let the slo-breach rule referee short-class p99
against long-prompt interference."""

import json
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.lm import generate as G
from distribuuuu_tpu.serve.admission import (
    AdmissionController,
    LongQueueFullError,
    QueueFullError,
)


def _tiny_gpt(seq_len=32, vocab=320, dtype=jnp.float32):
    from distribuuuu_tpu.models.gpt import GPT

    return GPT(
        vocab_size=vocab, seq_len=seq_len, dim=32, depth=2, num_heads=2,
        dtype=dtype,
    )


def _params(model, key=0):
    return model.init(
        jax.random.key(key), model.dummy_input(), train=False
    )["params"]


@pytest.fixture()
def f32(monkeypatch):
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    yield


def _long_engine(model, params, **kw):
    kw.setdefault("prompt_len", 8)
    kw.setdefault("max_new_tokens", 3)
    kw.setdefault("batch_tiles", [2])
    kw.setdefault("cache_tiles", [32])
    kw.setdefault("chunk_prefill", 4)
    return G.GenerateEngine(model, {"params": params}, **kw)


# ------------------------------------------------- admission reservation

def test_admission_long_reservation_unit():
    """Long requests need BOTH a free slot and a free long-class slot;
    short requests see only the total bound."""
    ctrl = AdmissionController(4, long_max_queue=2)
    ctrl.admit(3, 100.0)  # short at depth 3 of 4: fine
    ctrl.admit(1, 100.0, length_class="long", class_depth=1)
    with pytest.raises(LongQueueFullError,
                       match=r"2/2 long-class slots; SERVE\.MAX_QUEUE=4"):
        ctrl.admit(2, 100.0, length_class="long", class_depth=2)
    # the long rejection IS a QueueFullError — service layers that catch
    # the base class keep the retry-after frame shape byte-for-byte
    try:
        ctrl.admit(2, 123.0, length_class="long", class_depth=2)
    except QueueFullError as e:
        assert e.retry_after_ms == 123.0 and e.length_class == "long"
    # total bound still wins for every class
    with pytest.raises(QueueFullError):
        ctrl.admit(4, 100.0)
    with pytest.raises(QueueFullError):
        ctrl.admit(4, 100.0, length_class="long", class_depth=0)


def test_admission_reservation_validation_arithmetic():
    with pytest.raises(ValueError, match=r"4 >= 4"):
        AdmissionController(4, long_max_queue=4)
    with pytest.raises(ValueError, match=r"8 >= 4"):
        AdmissionController(4, long_max_queue=8)
    with pytest.raises(ValueError, match="≥ 0"):
        AdmissionController(4, long_max_queue=-1)
    # no reservation: plain bounded queue, long class never refused early
    ctrl = AdmissionController(2)
    ctrl.admit(1, 100.0, length_class="long", class_depth=1)


def test_engine_refuses_reservation_without_threshold(f32):
    model = _tiny_gpt()
    params = _params(model)
    with pytest.raises(ValueError,
                       match="without SERVE.LONG_PROMPT_THRESHOLD"):
        _long_engine(model, params, max_queue=4, long_max_queue=2)


# ------------------------------------------- engine-level starvation pin

def test_long_burst_cannot_starve_short_admission(f32):
    """THE pin: with the queue already holding its full long-class
    reservation, further long prompts backpressure while short prompts
    keep admitting — and every admitted request still completes."""
    model = _tiny_gpt()
    params = _params(model)
    eng = _long_engine(
        model, params, max_queue=3,
        long_prompt_threshold=8, long_max_queue=1,
    )
    rng = np.random.default_rng(21)
    long_p = rng.integers(0, 256, (10,)).astype(np.int32)
    short_p = rng.integers(0, 256, (3,)).astype(np.int32)
    # engine not started: the queue holds, making depth deterministic
    s_long = eng.submit(long_p)
    with pytest.raises(LongQueueFullError,
                       match=r"1/1 long-class slots; SERVE\.MAX_QUEUE=3"):
        eng.submit(long_p)
    s_short1 = eng.submit(short_p)  # short traffic unaffected
    s_short2 = eng.submit(short_p)
    st = eng.stats()
    assert st["queue_depth"] == 3 and st["queue_depth_long"] == 1
    assert st["long_threshold"] == 8 and st["long_max_queue"] == 1
    assert st["long_admitted"] == 1 and st["long_rejected"] == 1
    # the total bound still closes the queue for shorts too
    with pytest.raises(QueueFullError):
        eng.submit(short_p)
    eng.start()
    for s in (s_long, s_short1, s_short2):
        assert len(s.result(timeout=120.0)) >= 1
    eng.drain()


def test_queue_full_frame_shape_verbatim(f32):
    """The service layer's long-class rejection frame is byte-shape
    identical to the classic queue_full frame: {"error", "retry_after_ms"}
    and nothing else — clients and the router passthrough never learn a
    new shape."""
    from distribuuuu_tpu.lm import service as lm_service

    model = _tiny_gpt()
    params = _params(model)
    eng = _long_engine(
        model, params, max_queue=3,
        long_prompt_threshold=8, long_max_queue=1,
    )
    eng.submit(np.arange(10, dtype=np.int32))  # fill the reservation
    frames = []
    lm_service.handle_generate(
        eng, {"tokens": list(range(12))}, lambda b: frames.append(b)
    )
    assert len(frames) == 1
    rec = json.loads(frames[0])
    assert set(rec) == {"error", "retry_after_ms"}
    assert rec["error"] == "queue_full" and rec["retry_after_ms"] > 0
    eng.drain()


# --------------------------------------------------- router length classes

def test_router_classifies_generate_frames():
    from distribuuuu_tpu.serve import protocol
    from distribuuuu_tpu.serve.fleet.router import Router

    router = Router(long_prompt_threshold=8, short_p99_slo_ms=50.0,
                    long_p99_slo_ms=500.0)
    classify = router._classify_payload
    assert classify(
        protocol.ctrl_request("generate", tokens=list(range(10)))
    ) == "long"
    assert classify(
        protocol.ctrl_request("generate", tokens=[1, 2, 3])
    ) == "short"
    # text prompts count utf-8 bytes (the byte tokenizer's 1:1 identity)
    assert classify(
        protocol.ctrl_request("generate", text="x" * 9)
    ) == "long"
    assert classify(protocol.ctrl_request("generate", text="ab")) == "short"
    # non-generate ctrl frames and image payloads never classify
    assert classify(protocol.ctrl_request("stats")) is None
    assert classify(b"\xff\xd8rawjpegbytes") is None
    # classification off → everything is unclassified
    assert Router()._classify_payload(
        protocol.ctrl_request("generate", tokens=list(range(10)))
    ) is None


def test_router_per_class_stats_and_slo_rows():
    """Observed per-class latencies surface BOTH as a length_classes
    stats section and as `length:*` rows in the windowed models dict —
    the exact shape the slo-breach rule scans for targeted rows."""
    from distribuuuu_tpu.serve.fleet.router import Router

    router = Router(long_prompt_threshold=8, short_p99_slo_ms=50.0,
                    long_p99_slo_ms=500.0)
    rep = router.add_replica("127.0.0.1", 1)
    router.mark_routable(rep.id)
    for _ in range(5):
        router._observe(rep, 0.010, length_class="short")
    router._observe(rep, 0.300, length_class="long")
    router._count_rejected(None, length_class="long")
    win = router.window_stats(60.0)
    assert win["models"]["length:short"]["samples"] == 5
    assert win["models"]["length:short"]["target_ms"] == 50.0
    assert win["models"]["length:long"]["target_ms"] == 500.0
    assert win["models"]["length:long"]["p99_ms"] >= 300.0
    snap = router.stats()
    assert snap["long_prompt_threshold"] == 8
    lc = snap["length_classes"]
    assert lc["short"]["requests"] == 5 and lc["short"]["rejected"] == 0
    assert lc["long"]["requests"] == 1 and lc["long"]["rejected"] == 1
    assert lc["long"]["p99_slo_ms"] == 500.0
    # an unclassified router surfaces neither section
    from distribuuuu_tpu.serve.fleet.router import Router as R2

    assert "length_classes" not in R2().stats()


def test_router_busy_passthrough_counts_long_rejection(f32):
    """A long generate stream rejected by every replica passes the
    replica's queue_full frame through verbatim AND lands in the long
    class's rejected count — the campaign's backpressure evidence."""
    import socket

    from distribuuuu_tpu.serve import protocol
    from distribuuuu_tpu.serve.fleet.router import Router

    rep_listener = protocol.open_listener("127.0.0.1", 0)
    rep_port = rep_listener.getsockname()[1]

    def busy_replica():
        conn, _ = rep_listener.accept()
        with conn:
            protocol.recv_frame(conn)
            protocol.send_frame(conn, json.dumps(
                {"error": "queue_full", "retry_after_ms": 77.0}
            ).encode())

    rt = threading.Thread(target=busy_replica, daemon=True)
    rt.start()
    router = Router(request_timeout_s=10.0, long_prompt_threshold=8)
    rep = router.add_replica("127.0.0.1", rep_port)
    router.mark_routable(rep.id)
    listener = protocol.open_listener("127.0.0.1", 0)
    port = listener.getsockname()[1]
    stop = threading.Event()
    t = threading.Thread(
        target=router.serve, args=(listener, stop.is_set), daemon=True
    )
    t.start()
    try:
        with socket.create_connection(("127.0.0.1", port), timeout=10) as c:
            protocol.send_frame(c, protocol.ctrl_request(
                "generate", tokens=list(range(20))
            ))
            resp = json.loads(protocol.recv_frame(c))
        assert resp == {"error": "queue_full", "retry_after_ms": 77.0}
        assert router.stats()["length_classes"]["long"]["rejected"] == 1
    finally:
        stop.set()
        t.join(5)
        rep_listener.close()


def test_length_class_telemetry_schema(tmp_path):
    """fleet.length_class records land schema-valid in the span sink."""
    import glob

    from distribuuuu_tpu import telemetry
    from distribuuuu_tpu.serve.fleet.router import Router
    from distribuuuu_tpu.telemetry import schema

    cfg.OUT_DIR = str(tmp_path)
    telemetry.setup_from_cfg(cfg, rank=0)
    try:
        router = Router(long_prompt_threshold=8, long_p99_slo_ms=500.0)
        rep = router.add_replica("127.0.0.1", 1)
        router.mark_routable(rep.id)
        router._observe(rep, 0.010, length_class="short")
        router._observe(rep, 0.200, length_class="long")
        router.emit_telemetry()
    finally:
        from distribuuuu_tpu.telemetry import spans

        spans.close_telemetry()
    recs = []
    for p in glob.glob(str(tmp_path / "telemetry" / "rank*.jsonl")):
        with open(p) as f:
            recs.extend(json.loads(line) for line in f)
    lrecs = {r["length_class"]: r for r in recs
             if r.get("kind") == "fleet.length_class"}
    assert set(lrecs) == {"short", "long"}
    assert lrecs["long"]["threshold"] == 8
    assert lrecs["short"]["requests"] == 1
    for r in recs:
        schema.validate_record(r)
