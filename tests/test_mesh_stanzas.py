"""The mesh-stanza gate (ISSUE 9 satellite): every shipped
``config/*.yaml`` MESH stanza — and every stanza the sweep generates —
validates through the partition topology registry, and the DECLARED
layouts match the COMPILED shardings leaf for leaf (spec drift between
the declaration and what GSPMD actually places fails here, in tier-1,
not on a pod)."""

import glob
import os
import sys

import jax
import pytest

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer
from distribuuuu_tpu.parallel import mesh as mesh_lib
from distribuuuu_tpu.parallel.partition import specs, topology

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG_DIR = os.path.join(REPO, "config")
YAMLS = sorted(glob.glob(os.path.join(CONFIG_DIR, "*.yaml")))


def _is_model_yaml(path):
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    return "MODEL" in doc


@pytest.mark.parametrize(
    "path", [p for p in YAMLS if _is_model_yaml(p)],
    ids=[os.path.basename(p) for p in YAMLS if _is_model_yaml(p)],
)
def test_shipped_yaml_stanzas_validate_through_registry(path):
    """Each shipped model YAML merges clean and its (possibly default)
    MESH stanza resolves + validates on the 8-device mesh."""
    config.reset_cfg()
    cfg.merge_from_file(path)
    topo = topology.from_cfg(cfg, n_devices=8)
    assert topo.devices() == 8
    # the stanza the topology reproduces must round-trip through the
    # registry (generated YAMLs are written from exactly this dict)
    stanza = topo.mesh_stanza()
    config.reset_cfg()
    cfg.merge_from_file(path)
    for key, val in stanza.items():
        cfg.MESH[key] = val
    assert topology.from_cfg(cfg, n_devices=8).axes == topo.axes


def test_generated_sweep_stanzas_validate_through_registry():
    """Every stanza the mesh sweep generates (tools/mesh_sweep.py) is
    registry-valid by construction — enumerate → validate must agree."""
    tools = os.path.join(REPO, "tools")
    sys.path.insert(0, tools)
    try:
        import mesh_sweep
    finally:
        sys.path.remove(tools)

    config.reset_cfg()
    cases = mesh_sweep.generate_cases(8)
    assert len(cases) >= 20  # the space is genuinely enumerated
    for case in cases:
        config.reset_cfg()
        cfg.MODEL.ARCH = case["arch"]
        for key, val in case["stanza"].items():
            cfg.MESH[key] = val
        topo = topology.from_cfg(cfg, n_devices=8)
        assert topo.zero == case["zero"], case["name"]
    config.reset_cfg()


def _canon(sharding, axis_sizes):
    return specs.canonicalize(sharding.spec, axis_sizes)


def _assert_no_spec_drift(state, layout, mesh):
    """Declared layout vs the shardings GSPMD actually placed."""
    axis_sizes = {k: int(v) for k, v in dict(mesh.shape).items()}
    declared = jax.tree.leaves(layout["params"])
    placed = jax.tree.leaves(state.params)
    assert len(declared) == len(placed)
    for d, p in zip(declared, placed):
        assert _canon(p.sharding, axis_sizes) == _canon(d, axis_sizes), (
            f"param spec drift: declared {d.spec}, compiled {p.sharding.spec}"
        )
    # optimizer state: momentum copies rest in the declared opt layout
    declared_opt = jax.tree.leaves(layout["opt"])
    momenta = [
        leaf for leaf in jax.tree.leaves(state.opt_state)
        if hasattr(leaf, "sharding") and getattr(leaf, "ndim", 0) >= 1
        and leaf.shape  # skip scalars (step counters)
    ]
    # param-shaped trace copies flatten in params order: one for sgd
    # (momentum), two for adamw (mu, nu — the LM recipe) — each copy must
    # rest in the declared opt layout
    assert len(momenta) % len(declared_opt) == 0 and momenta
    for i, p in enumerate(momenta):
        d = declared_opt[i % len(declared_opt)]
        assert _canon(p.sharding, axis_sizes) == _canon(d, axis_sizes), (
            f"opt spec drift: declared {d.spec}, compiled {p.sharding.spec}"
        )


def test_gpt_yaml_stanza_trains_end_to_end(tmp_path):
    """ISSUE 12 acceptance: the LM trains from config/gpt_nano_moe.yaml's
    dp2·tp2·ep2 MESH stanza with ZERO new lowering code — the partition
    layer places everything from the LM SpecTable rules + annotations,
    the existing trainer step body runs the next-token CE, and declared
    vs compiled shardings agree leaf for leaf. Only benchmark geometry
    (seq len / batch) is overridden; the stanza is the YAML's."""
    import numpy as np

    from distribuuuu_tpu.data import construct_train_loader
    from distribuuuu_tpu.data.shards import tokens as token_shards
    from distribuuuu_tpu.parallel.partition import lowering
    from distribuuuu_tpu.utils.optim import construct_optimizer

    config.reset_cfg()
    cfg.merge_from_file(os.path.join(CONFIG_DIR, "gpt_nano_moe.yaml"))
    assert cfg.MESH.MODEL == 2 and cfg.MESH.EXPERT == 2  # the yaml stanza
    S = 16
    rng = np.random.default_rng(0)
    split = tmp_path / "train"
    docs = [
        bytes(rng.integers(32, 120, (200,)).astype(np.uint8))
        for _ in range(6)
    ]
    token_shards.write_token_shards(
        str(split), token_shards.pack_token_stream(docs, S), S,
    )
    cfg.LM.SEQ_LEN = S
    cfg.TRAIN.DATASET = str(tmp_path)
    cfg.TRAIN.BATCH_SIZE = 1  # per-chip; ×8 virtual devices per host
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    topo = trainer.check_trainer_mesh()
    assert topo.class_name() == "dp2·tp2·ep2"
    mesh = mesh_lib.mesh_from_cfg(cfg)
    model = trainer.build_model_from_cfg(topo)
    low = lowering.lower(
        model, construct_optimizer(), topk=5, mesh=mesh, topology=topo,
        im_size=cfg.TRAIN.IM_SIZE,
    )
    state = low.init_state(jax.random.key(0), cfg.TRAIN.IM_SIZE)
    # declared vs compiled shardings — the gate's teeth, on the LM
    _assert_no_spec_drift(state, low.layout, mesh)
    loader = construct_train_loader()
    loader.set_epoch(0)
    losses = []
    for i, hb in enumerate(loader):
        if i == 2:
            break
        state, metrics = low.train_step(state, low.put_batch(hb))
        losses.append(float(metrics["loss"]))
    assert len(losses) == 2 and all(np.isfinite(v) for v in losses)
    # the expert tensors really rest on the dedicated expert axis
    w_in = state.params["Block_1"]["MoeMlp_0"]["w_in"]
    assert "expert" in str(w_in.sharding.spec)
    # and the embedding landed the LM spec-table placement
    emb = state.params["tok_embed"]["embedding"]
    axis_sizes = {k: int(v) for k, v in dict(mesh.shape).items()}
    assert specs.canonicalize(emb.sharding.spec, axis_sizes) == \
        specs.canonicalize(jax.sharding.PartitionSpec(None, "model"),
                           axis_sizes)


def _token_batch(step: int, seq_len: int, n: int = 8):
    """Deterministic synthetic token batch (loader-shaped: input tokens as
    ``image``, next tokens as ``label``, per-sequence ``mask``)."""
    import numpy as np

    rng = np.random.default_rng(7_000 + step)
    toks = rng.integers(0, 320, (n, seq_len + 1)).astype(np.int32)
    return {
        "image": toks[:, :-1],
        "label": toks[:, 1:],
        "mask": np.ones((n,), np.float32),
    }


def test_gpt_sp_yaml_stanza_trains_end_to_end():
    """ISSUE 19 acceptance: the LM trains from config/gpt_nano_sp.yaml's
    dp2·sp4 MESH stanza — causal ring attention over the seq axis, token
    batches arriving (data, seq)-sharded per TOKEN_BATCH_TABLE — with
    zero declared-vs-compiled sharding drift, the loss trajectory in
    lockstep with the seq-UNSHARDED reference (same data, same init, dp
    only), and the compiled step's seq-axis collective-permute census
    inside the declared ring band (a missing hop = local-only attention =
    wrong math; this asserts the band the analyzer referees)."""
    import numpy as np

    from distribuuuu_tpu.analysis import hlo
    from distribuuuu_tpu.parallel.partition import lowering
    from distribuuuu_tpu.utils.optim import construct_optimizer

    S = 16

    def _train(path, expect_class):
        config.reset_cfg()
        cfg.merge_from_file(os.path.join(CONFIG_DIR, path))
        cfg.LM.SEQ_LEN = S
        cfg.DEVICE.COMPUTE_DTYPE = "float32"
        topo = trainer.check_trainer_mesh()
        assert topo.class_name() == expect_class
        mesh = mesh_lib.mesh_from_cfg(cfg)
        model = trainer.build_model_from_cfg(topo)
        low = lowering.lower(
            model, construct_optimizer(), topk=5, mesh=mesh, topology=topo,
            im_size=cfg.TRAIN.IM_SIZE,
        )
        state = low.init_state(jax.random.key(0), cfg.TRAIN.IM_SIZE)
        losses = []
        gb = None
        for it in range(3):
            gb = low.put_batch(_token_batch(it, S))
            state, m = low.train_step(state, gb)
            losses.append(float(m["loss"]))
        return topo, mesh, model, low, state, losses, gb

    topo, mesh, model, low, state, losses, gbatch = _train(
        "gpt_nano_sp.yaml", "dp2·sp4"
    )
    assert model.attn_impl == "ring" and model.mesh is not None
    assert np.isfinite(losses).all()
    # declared vs compiled shardings — the gate's teeth, on the sp stanza
    _assert_no_spec_drift(state, low.layout, mesh)
    # the token batch really lands (data, seq)-sharded; the rank-1 mask
    # stays on data alone (one shared spec could not express both)
    axis_sizes = {k: int(v) for k, v in dict(mesh.shape).items()}
    P = jax.sharding.PartitionSpec
    assert specs.canonicalize(gbatch["image"].sharding.spec, axis_sizes) \
        == specs.canonicalize(P("data", "seq"), axis_sizes)
    assert specs.canonicalize(gbatch["mask"].sharding.spec, axis_sizes) \
        == specs.canonicalize(P("data"), axis_sizes)

    # ring census: seq-axis collective-permutes of the COMPILED step stay
    # inside the declared band (specs.collective_expectations "ring")
    ring = specs.collective_expectations(low.layout, topo)["ring"]
    assert ring is not None and ring["attn_layers"] == 4  # gpt_nano depth
    text = low.train_step.lower(state, gbatch).compile().as_text()
    n_seq = sum(
        1 for op in hlo.collective_census(text, mesh)
        if op["kind"] == "collective-permute" and op["axes"] == ("seq",)
    )
    assert ring["min_permutes"] <= n_seq <= ring["max_permutes"], (
        n_seq, ring
    )

    # lockstep vs the seq-unsharded reference: same init key, same data,
    # dp-only mesh — early-window exactness + same family on step 3
    _, _, _, _, _, ref_losses, _ = _train("gpt_nano.yaml", "dp8")
    np.testing.assert_allclose(losses[:2], ref_losses[:2], rtol=0, atol=2e-2)
    assert abs(losses[2] - ref_losses[2]) < 0.5, (losses, ref_losses)
    config.reset_cfg()


def test_gpt_sp_refuses_indivisible_seq_len():
    """The sp-stanza refusal carries the arithmetic: a SEQ_LEN the seq
    axis does not divide refuses at build, not as silent replication."""
    config.reset_cfg()
    cfg.MODEL.ARCH = "gpt_nano"
    cfg.MODEL.NUM_CLASSES = 320
    cfg.MESH.DATA, cfg.MESH.SEQ = 2, 4
    cfg.LM.SEQ_LEN = 18  # 18 % 4 = 2
    topo = topology.from_cfg(cfg, n_devices=8)
    with pytest.raises(ValueError, match=r"18 % 4 = 2"):
        trainer.build_model_from_cfg(topo)
    config.reset_cfg()


@pytest.mark.parametrize(
    "arch,stanza",
    [
        ("resnet18", {"DATA": -1, "ZERO": 1}),
        ("resnet18", {"DATA": 4, "MODEL": 2, "ZERO": 1}),
        ("vit_tiny_moe", {"DATA": 2, "MODEL": 2, "EXPERT": 2, "ZERO": 1}),
        ("gpt_nano_moe", {"DATA": 2, "MODEL": 2, "EXPERT": 2, "ZERO": 1}),
        ("gpt_nano", {"DATA": 2, "SEQ": 4}),
    ],
    ids=["dp_zero1", "dp_tp_zero1", "dp_tp_ep_zero1", "lm_dp_tp_ep_zero1",
         "lm_dp_sp"],
)
def test_no_drift_between_declared_and_compiled_shardings(arch, stanza):
    """The gate's teeth: place real state through create_train_state and
    compare every leaf's compiled sharding against the declared layout
    (canonicalized — size-1 axes collapse)."""
    config.reset_cfg()
    cfg.MODEL.ARCH = arch
    cfg.MODEL.NUM_CLASSES = 10
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    for key, val in stanza.items():
        cfg.MESH[key] = val
    topo = trainer.check_trainer_mesh()
    mesh = mesh_lib.mesh_from_cfg(cfg)
    model = trainer.build_model_from_cfg(topo)
    layout = specs.state_layout(model, mesh, 32, topo.zero)
    state = trainer.create_train_state(
        model, jax.random.key(0), mesh, 32, layout=layout
    )
    _assert_no_spec_drift(state, layout, mesh)
