"""The mesh-stanza gate (ISSUE 9 satellite): every shipped
``config/*.yaml`` MESH stanza — and every stanza the sweep generates —
validates through the partition topology registry, and the DECLARED
layouts match the COMPILED shardings leaf for leaf (spec drift between
the declaration and what GSPMD actually places fails here, in tier-1,
not on a pod)."""

import glob
import os
import sys

import jax
import pytest

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer
from distribuuuu_tpu.parallel import mesh as mesh_lib
from distribuuuu_tpu.parallel.partition import specs, topology

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CONFIG_DIR = os.path.join(REPO, "config")
YAMLS = sorted(glob.glob(os.path.join(CONFIG_DIR, "*.yaml")))


def _is_model_yaml(path):
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    return "MODEL" in doc


@pytest.mark.parametrize(
    "path", [p for p in YAMLS if _is_model_yaml(p)],
    ids=[os.path.basename(p) for p in YAMLS if _is_model_yaml(p)],
)
def test_shipped_yaml_stanzas_validate_through_registry(path):
    """Each shipped model YAML merges clean and its (possibly default)
    MESH stanza resolves + validates on the 8-device mesh."""
    config.reset_cfg()
    cfg.merge_from_file(path)
    topo = topology.from_cfg(cfg, n_devices=8)
    assert topo.devices() == 8
    # the stanza the topology reproduces must round-trip through the
    # registry (generated YAMLs are written from exactly this dict)
    stanza = topo.mesh_stanza()
    config.reset_cfg()
    cfg.merge_from_file(path)
    for key, val in stanza.items():
        cfg.MESH[key] = val
    assert topology.from_cfg(cfg, n_devices=8).axes == topo.axes


def test_generated_sweep_stanzas_validate_through_registry():
    """Every stanza the mesh sweep generates (tools/mesh_sweep.py) is
    registry-valid by construction — enumerate → validate must agree."""
    tools = os.path.join(REPO, "tools")
    sys.path.insert(0, tools)
    try:
        import mesh_sweep
    finally:
        sys.path.remove(tools)

    config.reset_cfg()
    cases = mesh_sweep.generate_cases(8)
    assert len(cases) >= 20  # the space is genuinely enumerated
    for case in cases:
        config.reset_cfg()
        cfg.MODEL.ARCH = case["arch"]
        for key, val in case["stanza"].items():
            cfg.MESH[key] = val
        topo = topology.from_cfg(cfg, n_devices=8)
        assert topo.zero == case["zero"], case["name"]
    config.reset_cfg()


def _canon(sharding, axis_sizes):
    return specs.canonicalize(sharding.spec, axis_sizes)


def _assert_no_spec_drift(state, layout, mesh):
    """Declared layout vs the shardings GSPMD actually placed."""
    axis_sizes = {k: int(v) for k, v in dict(mesh.shape).items()}
    declared = jax.tree.leaves(layout["params"])
    placed = jax.tree.leaves(state.params)
    assert len(declared) == len(placed)
    for d, p in zip(declared, placed):
        assert _canon(p.sharding, axis_sizes) == _canon(d, axis_sizes), (
            f"param spec drift: declared {d.spec}, compiled {p.sharding.spec}"
        )
    # optimizer state: momentum copies rest in the declared opt layout
    declared_opt = jax.tree.leaves(layout["opt"])
    momenta = [
        leaf for leaf in jax.tree.leaves(state.opt_state)
        if hasattr(leaf, "sharding") and getattr(leaf, "ndim", 0) >= 1
        and leaf.shape  # skip scalars (step counters)
    ]
    # sgd: exactly one param-shaped trace copy, flattened in params order
    assert len(momenta) == len(declared_opt)
    for d, p in zip(declared_opt, momenta):
        assert _canon(p.sharding, axis_sizes) == _canon(d, axis_sizes), (
            f"opt spec drift: declared {d.spec}, compiled {p.sharding.spec}"
        )


@pytest.mark.parametrize(
    "arch,stanza",
    [
        ("resnet18", {"DATA": -1, "ZERO": 1}),
        ("resnet18", {"DATA": 4, "MODEL": 2, "ZERO": 1}),
        ("vit_tiny_moe", {"DATA": 2, "MODEL": 2, "EXPERT": 2, "ZERO": 1}),
    ],
    ids=["dp_zero1", "dp_tp_zero1", "dp_tp_ep_zero1"],
)
def test_no_drift_between_declared_and_compiled_shardings(arch, stanza):
    """The gate's teeth: place real state through create_train_state and
    compare every leaf's compiled sharding against the declared layout
    (canonicalized — size-1 axes collapse)."""
    config.reset_cfg()
    cfg.MODEL.ARCH = arch
    cfg.MODEL.NUM_CLASSES = 10
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    for key, val in stanza.items():
        cfg.MESH[key] = val
    topo = trainer.check_trainer_mesh()
    mesh = mesh_lib.mesh_from_cfg(cfg)
    model = trainer.build_model_from_cfg(topo)
    layout = specs.state_layout(model, mesh, 32, topo.zero)
    state = trainer.create_train_state(
        model, jax.random.key(0), mesh, 32, layout=layout
    )
    _assert_no_spec_drift(state, layout, mesh)
