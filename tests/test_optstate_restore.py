"""Auto-resume must restore the OPTIMIZER state, not silently discard it.

Regression test for a bug the ZeRO work exposed (r4): orbax restores
optax's namedtuple containers as plain dicts, `_place_like` then raised a
structure mismatch, and `_resume`'s graceful weights-only fallback (ref:
/root/reference/distribuuuu/utils.py:399-405 — meant for deliberately
weights-only checkpoints) swallowed it — so every auto-resume trained with
fresh momentum while logging only a warning. The pack/unpack protocol
(utils/checkpoint.pack_opt_state) rebuilds the exact optax structure
against the live optimizer; these tests pin momentum values THROUGH the
real resume path.
"""

import numpy as np
import jax
import pytest

pytestmark = pytest.mark.slow  # orbax save/restore cycles, ~45s each on this box

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer
from distribuuuu_tpu.parallel import mesh as mesh_lib, sharding as sharding_lib
from distribuuuu_tpu.utils import checkpoint as ckpt
from distribuuuu_tpu.utils.optim import construct_optimizer


def _setup(tmp_path, optimizer_kind="sgd"):
    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.BN_GROUP = 8
    cfg.OPTIM.BASE_LR = 0.05
    cfg.OPTIM.OPTIMIZER = optimizer_kind
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.OUT_DIR = str(tmp_path)
    mesh = mesh_lib.mesh_from_cfg(cfg)
    model = trainer.build_model_from_cfg()
    state = trainer.create_train_state(model, jax.random.key(0), mesh, 32)
    step = trainer.make_train_step(model, construct_optimizer(), topk=5)
    return mesh, model, state, step


def _batch(n=16):
    rng = np.random.default_rng(42)
    images = rng.standard_normal((n, 32, 32, 3)).astype(np.float32)
    labels = rng.integers(0, 10, size=(n,)).astype(np.int32)
    return {"image": images, "label": labels, "mask": np.ones((n,), np.float32)}


def _momentum_arrays(opt_state):
    return [
        np.asarray(x)
        for x in jax.tree.leaves(opt_state)
        if hasattr(x, "ndim") and x.ndim >= 2
    ]


@pytest.mark.parametrize("kind", ["sgd", "adamw"])
def test_resume_restores_momentum_exactly(tmp_path, kind):
    mesh, model, state, step = _setup(tmp_path, kind)
    batch = sharding_lib.shard_batch(mesh, _batch())
    state, _ = step(state, batch)  # momentum now nonzero
    saved_momentum = _momentum_arrays(state.opt_state)
    assert any(np.abs(m).max() > 0 for m in saved_momentum)
    ckpt.save_checkpoint(trainer._state_tree(state), 0, 11.0, False)

    # fresh process-equivalent: new state, then the REAL resume path
    fresh = trainer.create_train_state(model, jax.random.key(1), mesh, 32)
    resumed, start_epoch, best_acc1, pending, _ = trainer._resume(fresh, mesh)
    assert start_epoch == 1 and best_acc1 == 11.0 and pending is None
    assert int(resumed.step) == 1
    # the optax container structure survived (namedtuples, not dicts)
    assert jax.tree.structure(resumed.opt_state) == jax.tree.structure(
        state.opt_state
    )
    for a, b in zip(saved_momentum, _momentum_arrays(resumed.opt_state)):
        np.testing.assert_array_equal(a, b)


def test_resume_mismatched_optimizer_falls_back_gracefully(tmp_path):
    """A checkpoint saved with sgd resumed under adamw: leaf counts differ,
    unpack refuses, and the documented weights-only fallback applies
    (fresh optimizer, params still restored)."""
    mesh, model, state, step = _setup(tmp_path, "sgd")
    batch = sharding_lib.shard_batch(mesh, _batch())
    state, _ = step(state, batch)
    ckpt.save_checkpoint(trainer._state_tree(state), 0, 0.0, False)

    cfg.OPTIM.OPTIMIZER = "adamw"
    model2 = trainer.build_model_from_cfg()
    fresh = trainer.create_train_state(model2, jax.random.key(1), mesh, 32)
    resumed, start_epoch, _, _, _ = trainer._resume(fresh, mesh)
    assert start_epoch == 1
    # params came from the checkpoint…
    np.testing.assert_array_equal(
        np.asarray(jax.tree.leaves(resumed.params)[0]),
        np.asarray(jax.tree.leaves(state.params)[0]),
    )
    # …optimizer state did not (fresh adamw moments are zero)
    for m in _momentum_arrays(resumed.opt_state):
        assert np.abs(m).max() == 0


def test_unpack_rejects_shape_mismatch():
    tmpl = {"a": np.zeros((2, 3)), "b": np.zeros((4,))}
    stored = ckpt.pack_opt_state({"a": np.ones((2, 3)), "b": np.ones((5,))})
    with pytest.raises(ValueError, match="shape"):
        ckpt.unpack_opt_state(tmpl, stored)
    stored2 = ckpt.pack_opt_state({"a": np.ones((2, 3))})
    with pytest.raises(ValueError, match="leaf count"):
        ckpt.unpack_opt_state(tmpl, stored2)
