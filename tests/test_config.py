"""Config system tests (semantics per ref /root/reference/distribuuuu/config.py)."""

import glob
import os

import pytest
import yaml

from distribuuuu_tpu import config
from distribuuuu_tpu.config import CfgNode, cfg

CONFIG_DIR = os.path.join(os.path.dirname(__file__), "..", "config")


def _arch_yamls():
    """config/ also ships non-arch YAMLs (the monitor's alert rules —
    validated by tests/test_monitor.py instead); only files in the cfg
    schema (a MODEL node) go through the merge path here."""
    out = []
    for path in sorted(glob.glob(os.path.join(CONFIG_DIR, "*.yaml"))):
        with open(path) as f:
            doc = yaml.safe_load(f) or {}
        if "MODEL" in doc:
            out.append(path)
    return out


def test_defaults_tree():
    assert cfg.MODEL.ARCH == "resnet18"
    assert cfg.MODEL.NUM_CLASSES == 1000
    assert cfg.OPTIM.MOMENTUM == 0.9
    assert cfg.OPTIM.NESTEROV is True
    assert cfg.TRAIN.IM_SIZE == 224
    assert cfg.TEST.IM_SIZE == 256
    assert cfg.RNG_SEED is None


@pytest.mark.parametrize("path", _arch_yamls())
def test_all_shipped_yamls_parse(path):
    config.merge_from_file(path)
    stem = os.path.splitext(os.path.basename(path))[0]
    # a stanza is named for its arch, or is an {arch}_{mesh-variant}
    # recipe of the same arch (config/gpt_nano_sp.yaml — same model,
    # only the MESH stanza moves); either way OUT_DIR tracks the stem
    # so two shipped recipes never write into each other's run dir
    assert stem == cfg.MODEL.ARCH or stem.startswith(cfg.MODEL.ARCH + "_")
    assert cfg.OUT_DIR == f"./{stem}"


def test_reference_schema_parses_unchanged(tmp_path):
    """A YAML in the reference's exact schema (incl. CUDNN keys) must merge."""
    y = tmp_path / "ref.yaml"
    y.write_text(
        "CUDNN:\n  BENCHMARK: true\n  DETERMINISTIC: false\n"
        "MODEL:\n  ARCH: resnet50\n  WEIGHTS: null\n"
        "OPTIM:\n  BASE_LR: 0.2\n  STEPS: [30, 60, 90]\n"
        "RNG_SEED: null\n"
    )
    config.merge_from_file(str(y))
    assert cfg.MODEL.ARCH == "resnet50"
    assert cfg.CUDNN.BENCHMARK is True
    assert cfg.OPTIM.STEPS == [30, 60, 90]


def test_merge_from_list_typed():
    cfg.merge_from_list(["OPTIM.BASE_LR", "0.4", "TRAIN.BATCH_SIZE", "64"])
    assert cfg.OPTIM.BASE_LR == 0.4
    assert cfg.TRAIN.BATCH_SIZE == 64
    # None-slot accepts str and int
    cfg.merge_from_list(["MODEL.WEIGHTS", "w.ckpt", "RNG_SEED", "3"])
    assert cfg.MODEL.WEIGHTS == "w.ckpt"
    assert cfg.RNG_SEED == 3


def test_merge_rejects_unknown_key():
    with pytest.raises(KeyError):
        cfg.merge_from_list(["NOPE.KEY", "1"])


def test_merge_rejects_type_mismatch():
    with pytest.raises(ValueError):
        cfg.merge_from_list(["MODEL.ARCH", "[1,2]"])


def test_freeze_blocks_writes():
    cfg.freeze()
    with pytest.raises(AttributeError):
        cfg.MODEL.ARCH = "x"
    cfg.defrost()
    cfg.MODEL.ARCH = "resnet34"
    assert cfg.MODEL.ARCH == "resnet34"


def test_dump_roundtrip(tmp_path):
    cfg.defrost()
    cfg.OUT_DIR = str(tmp_path)
    cfg.OPTIM.BASE_LR = 0.8
    path = config.dump_cfg()
    fresh = CfgNode()
    import yaml

    loaded = yaml.safe_load(open(path))
    assert loaded["OPTIM"]["BASE_LR"] == 0.8


def test_load_cfg_fom_args(tmp_path):
    path = os.path.join(CONFIG_DIR, "resnet50.yaml")
    config.load_cfg_fom_args(argv=["--cfg", path, "OPTIM.MAX_EPOCH", "5"])
    assert cfg.MODEL.ARCH == "resnet50"
    assert cfg.OPTIM.MAX_EPOCH == 5


def test_reset_cfg():
    cfg.merge_from_list(["MODEL.ARCH", "resnet50"])
    config.reset_cfg()
    assert cfg.MODEL.ARCH == "resnet18"
