"""Sharded multi-host async save across real OS processes (slow tier,
ISSUE 18).

Two ranks × 2 virtual devices with MESH.ZERO=3 give genuinely
cross-host-sharded train state — the configuration whose async save PR 11
degraded to a synchronous collective (MultiHostSnapshotError). The
sharded protocol (asyncplane/committer.py) has each host's committer
thread write its OWN addressable shards under the existing commit
barrier. The pins here are the acceptance contract:

- the async run on the pod writes per-host shard files + layouts, the
  MANIFEST records the sharding, and verify_checkpoint covers the shard
  files through the ordinary digest walk;
- a 2-process verifier restores the sharded checkpoint onto the SAME
  topology and compares it leaf-by-leaf BIT-IDENTICAL to the synchronous
  collective save it replaces (same seed, same stream, concurrent eval
  in both runs — only CHECKPOINT.ASYNC differs);
- a full-group restart resumes from the sharded checkpoint through the
  normal trainer path and finishes — elastic restore, no orbax topology
  pin.

The async run also runs TRAIN.CONCURRENT_EVAL, so the cross-host
dispatch ring (asyncplane/ring.py) carries real traffic here: the ring
record lands in telemetry with zero deadline misses.
"""

import json
import os
import re
import sys

import pytest

import test_multiprocess_e2e as mp

REPO = mp.REPO
sys.path.insert(0, os.path.join(REPO, "tools"))

WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("DTPU_TEST_NDEV", "2")
).strip()
import jax
jax.config.update("jax_platforms", "cpu")

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer

out_dir, mode, max_epoch = sys.argv[1], sys.argv[2], int(sys.argv[3])
config.reset_cfg()
cfg.MODEL.ARCH = "resnet18"
cfg.MODEL.NUM_CLASSES = 10
cfg.MODEL.DUMMY_INPUT = True
cfg.DEVICE.COMPUTE_DTYPE = "float32"
cfg.TRAIN.BATCH_SIZE = 2
cfg.TRAIN.IM_SIZE = 16
cfg.TRAIN.PRINT_FREQ = 32
cfg.TEST.BATCH_SIZE = 16
cfg.TEST.IM_SIZE = 16
cfg.OPTIM.MAX_EPOCH = max_epoch
cfg.RNG_SEED = 0
cfg.MESH.ZERO = 3
cfg.OUT_DIR = out_dir
cfg.CHECKPOINT.ASYNC = mode == "async"
# concurrent eval in BOTH modes: the async/sync comparison isolates the
# save protocol (sharded vs collective), and best/epoch bookkeeping —
# which conc eval shifts by one boundary — stays identical across runs
cfg.TRAIN.CONCURRENT_EVAL = True
if len(sys.argv) > 4:
    cfg.merge_from_list(sys.argv[4:])
best = trainer.train_model()
print(f"WORKER_DONE rank={jax.process_index()} best={best:.3f}", flush=True)
"""

# Restores both checkpoints on the live 2-process topology and compares
# leaf-for-leaf: the sharded reassembly (host numpy) vs the synchronous
# collective restore (cross-host jax.Arrays, allgathered). Bitwise, via
# tobytes() — bfloat16 and float32 alike.
VERIFIER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("DTPU_TEST_NDEV", "2")
).strip()
import jax, numpy as np
jax.config.update("jax_platforms", "cpu")
from jax.experimental import multihost_utils

import distribuuuu_tpu.config as config
from distribuuuu_tpu.parallel import mesh as mesh_lib
mesh_lib.setup_distributed()
from distribuuuu_tpu.utils import checkpoint as ckpt

sharded_path, sync_path = sys.argv[1], sys.argv[2]
a = ckpt.load_checkpoint(sharded_path)
s = ckpt.load_checkpoint(sync_path)
s = jax.tree.map(
    lambda x: multihost_utils.process_allgather(x, tiled=True)
    if isinstance(x, jax.Array) and not x.is_fully_addressable
    else np.asarray(x),
    s,
)
la = jax.tree_util.tree_flatten_with_path(a)[0]
ls = jax.tree_util.tree_flatten_with_path(s)[0]
assert [k for k, _ in la] == [k for k, _ in ls], "leaf paths differ"
bad = 0
for (k, va), (_, vs) in zip(la, ls):
    if isinstance(va, str) or isinstance(vs, str):
        if str(va) != str(vs):
            bad += 1
        continue
    va, vs = np.asarray(va), np.asarray(vs)
    if va.dtype != vs.dtype and va.ndim == 0:
        # orbax's legacy restore WIDENS host scalars (float32->float64,
        # int32->int64); the sharded reassembly preserves the
        # manifest-recorded dtype. Accept only a lossless widening of
        # the identical value.
        down = vs.astype(va.dtype)
        if down.astype(vs.dtype).tobytes() == vs.tobytes():
            vs = down
    if va.shape != vs.shape or va.dtype != vs.dtype \
            or va.tobytes() != vs.tobytes():
        print("MISMATCH", jax.tree_util.keystr(k), va.dtype, vs.dtype,
              va.shape, vs.shape, flush=True)
        bad += 1
print(f"VERIFY rank={jax.process_index()} leaves={len(la)} "
      f"mismatches={bad}", flush=True)
assert bad == 0
"""


def _run_group(tmp_path, script, args, tag):
    procs, logs = mp._launch_group(
        tmp_path, script, args, nprocs=2, ndev=2,
        log_name=lambda rank, port: f"{tag}{rank}_{port}.log",
    )
    outs = []
    for p, log in zip(procs, logs):
        p.wait(timeout=900)
        log.seek(0)
        outs.append(log.read())
        log.close()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"{tag} rank {rank} failed:\n{out[-3000:]}"
    return outs


@pytest.mark.slow
def test_sharded_async_save_matches_sync_collective_and_restores(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    verifier = tmp_path / "verifier.py"
    verifier.write_text(VERIFIER)
    out_async = str(tmp_path / "out_async")
    out_sync = str(tmp_path / "out_sync")

    # ---- the async sharded run (ring + concurrent eval + async save) ----
    outs = _run_group(tmp_path, worker, (out_async, "async", "1"), "async")
    assert all("WORKER_DONE" in o for o in outs)
    ep0 = os.path.join(out_async, "checkpoints", "ckpt_ep_000")
    files = sorted(os.listdir(ep0))
    assert {"MANIFEST.json", "SHARDS_host0.json", "SHARDS_host1.json",
            "shards_host0.npz", "shards_host1.npz"} <= set(files), files
    man = json.load(open(os.path.join(ep0, "MANIFEST.json")))
    assert man["sharded"]["hosts"] == 2, man.get("sharded")
    assert man["sharded"]["files"] == ["shards_host0.npz",
                                       "shards_host1.npz"]
    from distribuuuu_tpu.resilience import manifest as manifest_lib

    ok, reason = manifest_lib.verify_checkpoint(ep0)
    assert ok, reason
    # the ring carried this run's dispatches: records on both hosts,
    # nobody wedged or detached
    ring_recs = []
    for rank in (0, 1):
        tpath = os.path.join(out_async, "telemetry",
                             f"rank{rank:05d}.jsonl")
        recs = [json.loads(ln) for ln in open(tpath).read().splitlines()]
        ring_recs.append(
            [r for r in recs if r.get("kind") == "dispatch.ring"]
        )
        assert any(r.get("kind") == "ckpt.shard" for r in recs), tpath
    assert ring_recs[0] and ring_recs[0][-1]["role"] == "leader"
    assert ring_recs[1] and ring_recs[1][-1]["role"] == "follower"
    for recs in ring_recs:
        assert recs[-1]["wedged"] is False
        assert recs[-1]["detached"] is False
        assert recs[-1]["deadline_misses"] == 0

    # ---- the synchronous collective baseline it replaces ----
    _run_group(tmp_path, worker, (out_sync, "sync", "1"), "sync")
    sync_ep0 = os.path.join(out_sync, "checkpoints", "ckpt_ep_000")
    assert not os.path.exists(os.path.join(sync_ep0, "SHARDS_host0.json"))

    # ---- bit-identity on the SAME topology ----
    outs = _run_group(tmp_path, verifier, (ep0, sync_ep0), "verify")
    for out in outs:
        m = re.search(r"VERIFY rank=\d leaves=(\d+) mismatches=(\d+)", out)
        assert m, out[-2000:]
        assert int(m.group(1)) > 100, out[-500:]  # a real ZeRO-3 tree
        assert int(m.group(2)) == 0, out[-2000:]

    # ---- elastic restart: resume from the sharded save, finish ----
    # (NONFINITE=skip: this toy config NaNs mid-epoch-1 after ANY resume
    # — sharded or sync collective alike, a pre-existing trainer-config
    # behavior — and the pin here is the restore path, not the loss)
    outs = _run_group(
        tmp_path, worker,
        (out_async, "async", "2", "TRAIN.NONFINITE", "skip"), "restart",
    )
    assert re.search(r"resumed from .*ckpt_ep_000", outs[0]), outs[0][-2000:]
    assert all("WORKER_DONE" in o for o in outs)
    names = sorted(os.listdir(os.path.join(out_async, "checkpoints")))
    assert any(n.startswith("ckpt_ep_001") and ".corrupt" not in n
               for n in names), names
    assert os.path.isfile(os.path.join(
        out_async, "checkpoints", "ckpt_ep_001", "shards_host1.npz"))
