"""Torch-checkpoint ingestion: numerics oracles + structural round trips.

The converter aligns torch modules to flax modules by kind and definition
order (utils/torch_ingest.py). These tests check (a) exact numerics of each
layer-kind conversion against torch's own forward (torch CPU is the oracle),
(b) full-model structural round trips for the archs the reference ships
pretrained weights for (ResNet/DenseNet families), and (c) loud failure on
architecture mismatch.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distribuuuu_tpu import models
from distribuuuu_tpu.utils import torch_ingest

pytestmark = pytest.mark.slow  # multi-minute on the 1-core CPU mesh

torch = pytest.importorskip("torch")


# ---------------------------------------------------------------------------
# helpers: inverse transform (flax → torch state_dict) for round trips
# ---------------------------------------------------------------------------


def randomize(tree, seed=0):
    """Replace every leaf with random values (so round trips are meaningful:
    init leaves BN scales at 1, biases at 0, which would hide swaps).

    Order-preserving manual walk — jax.tree.map would rebuild dicts with
    sorted keys and destroy the definition order the converter aligns on.
    Also unwraps flax Partitioned boxes."""
    rng = np.random.default_rng(seed)

    def walk(node):
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        v = torch_ingest._unwrap(node)
        return np.asarray(rng.standard_normal(np.shape(v)) * 0.5 + 0.1, np.float32)

    return walk(tree)


def flax_to_torch_sd(variables) -> dict:
    """Build a torch-convention state_dict from definition-ordered flax
    variables — the exact inverse of the converter's layout mapping."""
    sd = {}
    idx = 0
    for kind, path, leaves in torch_ingest._flax_slots(
        variables["params"], variables["batch_stats"]
    ):
        prefix = f"m{idx:03d}"
        idx += 1
        if kind == "conv":
            sd[f"{prefix}.weight"] = np.transpose(
                np.asarray(leaves["kernel"]), (3, 2, 0, 1)
            )
            if "bias" in leaves:
                sd[f"{prefix}.bias"] = np.asarray(leaves["bias"])
        elif kind == "linear":
            sd[f"{prefix}.weight"] = np.transpose(np.asarray(leaves["kernel"]))
            sd[f"{prefix}.bias"] = np.asarray(leaves["bias"])
        elif kind == "bn":
            sd[f"{prefix}.weight"] = np.asarray(leaves["scale"])
            sd[f"{prefix}.bias"] = np.asarray(leaves["bias"])
            if "mean" in leaves:  # LayerNorm slots carry no running stats
                sd[f"{prefix}.running_mean"] = np.asarray(leaves["mean"])
                sd[f"{prefix}.running_var"] = (
                    np.abs(np.asarray(leaves["var"])) + 0.5
                )
                sd[f"{prefix}.num_batches_tracked"] = np.asarray(7)
        elif kind == "embed":
            # path ends with the leaf name (rel_height, pos_embed, ...)
            sd[f"{prefix}.{path[-1]}"] = np.asarray(leaves[path[-1]])
        else:
            raise AssertionError(f"unexpected slot kind {kind} at {path}")
    return sd


def assert_trees_equal(a, b):
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = jax.tree_util.tree_leaves_with_path(b)
    assert [k for k, _ in la] == [k for k, _ in lb]
    for (k, x), (_, y) in zip(la, lb):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=jax.tree_util.keystr(k)
        )


# ---------------------------------------------------------------------------
# numerics oracles vs torch forward
# ---------------------------------------------------------------------------


def test_convbn_numerics_match_torch():
    """Converted conv+BN weights reproduce torch's eval-mode forward."""
    from distribuuuu_tpu.models.layers import ConvBN

    tconv = torch.nn.Conv2d(3, 8, 3, stride=2, padding=1, bias=False)
    tbn = torch.nn.BatchNorm2d(8)
    with torch.no_grad():
        tbn.weight.copy_(torch.rand(8) + 0.5)
        tbn.bias.copy_(torch.rand(8) - 0.5)
        tbn.running_mean.copy_(torch.rand(8))
        tbn.running_var.copy_(torch.rand(8) + 0.5)
    tconv.eval(), tbn.eval()

    x = np.random.default_rng(0).standard_normal((2, 10, 10, 3)).astype(np.float32)
    with torch.no_grad():
        want = (
            tbn(tconv(torch.from_numpy(np.transpose(x, (0, 3, 1, 2)))))
            .numpy()
            .transpose(0, 2, 3, 1)
        )

    model = ConvBN(8, (3, 3), 2, dtype=jnp.float32)
    variables = model.init(jax.random.key(0), jnp.asarray(x), train=False)
    sd = {
        "conv.weight": tconv.weight.detach().numpy(),
        "bn.weight": tbn.weight.detach().numpy(),
        "bn.bias": tbn.bias.detach().numpy(),
        "bn.running_mean": tbn.running_mean.numpy(),
        "bn.running_var": tbn.running_var.numpy(),
    }
    conv = torch_ingest.convert_state_dict(sd, variables)
    got = model.apply(
        {"params": conv["params"], "batch_stats": conv["batch_stats"]},
        jnp.asarray(x),
        train=False,
    )
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_linear_numerics_match_torch():
    from distribuuuu_tpu.models.layers import Dense

    tfc = torch.nn.Linear(12, 5)
    x = np.random.default_rng(1).standard_normal((3, 12)).astype(np.float32)
    with torch.no_grad():
        want = tfc(torch.from_numpy(x)).numpy()

    model = Dense(5, dtype=jnp.float32)
    variables = model.init(jax.random.key(0), jnp.asarray(x))
    sd = {
        "fc.weight": tfc.weight.detach().numpy(),
        "fc.bias": tfc.bias.detach().numpy(),
    }
    conv = torch_ingest.convert_state_dict(sd, variables)
    got = model.apply({"params": conv["params"]}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# full-model round trips (the archs with reference pretrained weights)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "arch",
    [
        "resnet18", "resnet50", "densenet121", "botnet50", "vit_tiny",
        "regnety_160", "efficientnet_b0",
    ],
)
def test_full_model_roundtrip(arch):
    """botnet50/vit_tiny exercise the 'embed' slot kind (rel_height/
    rel_width, pos_embed) that r1 refused (VERDICT r1 item 5);
    regnety_160/efficientnet_b0 exercise the depthwise-conv ([O,1,kh,kw])
    and biased-SE-1x1 layouts the published timm baselines need
    (VERDICT r2 #5)."""
    kw = {}
    if arch == "botnet50":
        kw["fmap_size"] = (4, 4)  # attention grid for the 64px test input
    model = models.build_model(arch, num_classes=10, dtype=jnp.float32, **kw)
    variables = torch_ingest.ordered_variables(model)
    variables = {
        "params": randomize(variables["params"], seed=3),
        "batch_stats": randomize(variables.get("batch_stats", {}), seed=4),
    }
    sd = flax_to_torch_sd(variables)
    conv = torch_ingest.convert_state_dict(sd, variables)
    # abs() in the inverse keeps var positive; compare through the same map
    want_stats = jax.tree.map(np.asarray, variables["batch_stats"])
    for (k, x), (_, y) in zip(
        jax.tree_util.tree_leaves_with_path(conv["batch_stats"]),
        jax.tree_util.tree_leaves_with_path(want_stats),
    ):
        if jax.tree_util.keystr(k).endswith("['var']"):
            continue  # var was abs+0.5'd in the inverse; skip exact check
        np.testing.assert_array_equal(np.asarray(x), y)
    assert_trees_equal(conv["params"], variables["params"])

    # the converted tree must actually run
    out = model.apply(
        {"params": conv["params"], "batch_stats": conv["batch_stats"]},
        jnp.ones((1, 64, 64, 3)),
        train=False,
    )
    assert out.shape == (1, 10)


def test_depthwise_se_numerics_match_torch():
    """Depthwise conv + squeeze-excite weights ingested from torch
    reproduce torch's forward exactly — the two layouts where order-based
    alignment could plausibly misalign (VERDICT r2 #5): depthwise kernels
    ([C,1,kh,kw] ↔ [kh,kw,1,C]) and SE's biased 1×1 convs."""
    import flax.linen as nn

    from distribuuuu_tpu.models.layers import SqueezeExcite

    C, se_w = 8, 4

    class TorchDWSE(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.dw = torch.nn.Conv2d(C, C, 3, padding=1, groups=C, bias=False)
            self.fc1 = torch.nn.Conv2d(C, se_w, 1)
            self.fc2 = torch.nn.Conv2d(se_w, C, 1)

        def forward(self, x):
            x = self.dw(x)
            s = x.mean((2, 3), keepdim=True)
            s = torch.sigmoid(self.fc2(torch.relu(self.fc1(s))))
            return x * s

    class FlaxDWSE(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.Conv(
                C, (3, 3), feature_group_count=C, use_bias=False,
                dtype=jnp.float32, param_dtype=jnp.float32,
            )(x)
            return SqueezeExcite(se_w, act=nn.relu, dtype=jnp.float32)(x)

    tmod = TorchDWSE().eval()
    x = np.random.default_rng(9).standard_normal((2, 6, 6, C)).astype(np.float32)
    with torch.no_grad():
        want = (
            tmod(torch.from_numpy(np.transpose(x, (0, 3, 1, 2))))
            .numpy().transpose(0, 2, 3, 1)
        )

    fmod = FlaxDWSE()
    variables = fmod.init(jax.random.key(0), jnp.asarray(x))
    sd = {k: v.detach().numpy() for k, v in tmod.state_dict().items()}
    conv = torch_ingest.convert_state_dict(sd, variables)
    got = fmod.apply({"params": conv["params"]}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_botnet_mhsa_numerics_match_torch():
    """Relative-position MHSA weights ingested from a torch state_dict
    reproduce torch's own forward. The torch oracle computes the
    Shaw-style 2D relative logits by explicit gather indexing
    (logit[i,j] = q_i·(rel_h[Δy]+rel_w[Δx])) — an independent formulation
    of the math the flax side implements with the pad-reshape trick."""
    import torch.nn.functional as F

    from distribuuuu_tpu.models.botnet import MHSA2D

    H = W = 4
    heads, dqk, dv = 2, 8, 8
    model = MHSA2D(
        fmap_size=(H, W), heads=heads, dim_qk=dqk, dim_v=dv,
        rel_pos_emb=True, attn_impl="xla", dtype=jnp.float32,
    )
    rng = np.random.default_rng(11)
    x = rng.standard_normal((2, H, W, 12)).astype(np.float32) * 0.5
    variables = model.init(jax.random.key(0), jnp.asarray(x))
    params = randomize(variables["params"], seed=12)

    # ingest a torch-convention state_dict carrying those exact weights
    sd = flax_to_torch_sd({"params": params, "batch_stats": {}})
    conv = torch_ingest.convert_state_dict(sd, {"params": params})
    got = np.asarray(
        model.apply({"params": conv["params"]}, jnp.asarray(x))
    )

    # torch oracle forward from the same state_dict
    keys = list(sd)
    w_qk = torch.from_numpy(np.ascontiguousarray(sd[keys[0]]))  # [O,C,1,1]
    w_v = torch.from_numpy(np.ascontiguousarray(sd[keys[1]]))
    rel_h = torch.from_numpy(np.asarray(params["rel_height"]))
    rel_w = torch.from_numpy(np.asarray(params["rel_width"]))
    xt = torch.from_numpy(np.transpose(x, (0, 3, 1, 2)))
    B = xt.shape[0]
    qk = F.conv2d(xt, w_qk)
    vv = F.conv2d(xt, w_v)
    q, k = qk.chunk(2, dim=1)

    def heads_first(t, d):
        return t.reshape(B, heads, d, H * W).transpose(2, 3)  # [B,h,HW,d]

    q, k, vv = heads_first(q, dqk), heads_first(k, dqk), heads_first(vv, dv)
    qs = q * (dqk ** -0.5)
    content = qs @ k.transpose(-1, -2)
    ys, xs = torch.meshgrid(
        torch.arange(H), torch.arange(W), indexing="ij"
    )
    ys, xs = ys.reshape(-1), xs.reshape(-1)
    dy = ys[None, :] - ys[:, None] + H - 1  # key minus query
    dx = xs[None, :] - xs[:, None] + W - 1
    pos = torch.einsum("bhid,ijd->bhij", qs, rel_h[dy] + rel_w[dx])
    attn = torch.softmax(content + pos, dim=-1)
    out = (attn @ vv).transpose(1, 2).reshape(B, H, W, heads * dv)
    np.testing.assert_allclose(got, out.numpy(), rtol=2e-4, atol=2e-5)


def test_reference_checkpoint_format_and_module_prefix(tmp_path):
    """torch.save'd reference-style checkpoints ({'state_dict': ...} with DDP
    'module.' prefixes) load through the file path."""
    model = models.build_model("resnet18", num_classes=10, dtype=jnp.float32)
    variables = torch_ingest.ordered_variables(model)
    sd = flax_to_torch_sd(variables)
    wrapped = {
        "epoch": 3,
        "state_dict": {f"module.{k}": torch.from_numpy(np.asarray(v)) for k, v in sd.items()},
    }
    path = str(tmp_path / "ckpt_ep_003.pth.tar")
    torch.save(wrapped, path)

    assert torch_ingest.is_torch_checkpoint(path)
    loaded = torch_ingest.load_torch_state_dict(path)
    assert list(loaded) == list(sd)  # order preserved, prefix stripped
    from flax.linen import meta

    conv = torch_ingest.convert_state_dict(loaded, variables)
    assert_trees_equal(
        conv["params"],
        jax.tree.map(np.asarray, meta.unbox(variables["params"])),
    )


def test_arch_mismatch_raises():
    r18 = models.build_model("resnet18", num_classes=10, dtype=jnp.float32)
    r34 = models.build_model("resnet34", num_classes=10, dtype=jnp.float32)
    sd = flax_to_torch_sd(torch_ingest.ordered_variables(r18))
    with pytest.raises(ValueError):
        torch_ingest.convert_state_dict(sd, torch_ingest.ordered_variables(r34))
