"""Torch-checkpoint ingestion: numerics oracles + structural round trips.

The converter aligns torch modules to flax modules by kind and definition
order (utils/torch_ingest.py). These tests check (a) exact numerics of each
layer-kind conversion against torch's own forward (torch CPU is the oracle),
(b) full-model structural round trips for the archs the reference ships
pretrained weights for (ResNet/DenseNet families), and (c) loud failure on
architecture mismatch.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distribuuuu_tpu import models
from distribuuuu_tpu.utils import torch_ingest

torch = pytest.importorskip("torch")


# ---------------------------------------------------------------------------
# helpers: inverse transform (flax → torch state_dict) for round trips
# ---------------------------------------------------------------------------


def randomize(tree, seed=0):
    """Replace every leaf with random values (so round trips are meaningful:
    init leaves BN scales at 1, biases at 0, which would hide swaps).

    Order-preserving manual walk — jax.tree.map would rebuild dicts with
    sorted keys and destroy the definition order the converter aligns on.
    Also unwraps flax Partitioned boxes."""
    rng = np.random.default_rng(seed)

    def walk(node):
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        v = torch_ingest._unwrap(node)
        return np.asarray(rng.standard_normal(np.shape(v)) * 0.5 + 0.1, np.float32)

    return walk(tree)


def flax_to_torch_sd(variables) -> dict:
    """Build a torch-convention state_dict from definition-ordered flax
    variables — the exact inverse of the converter's layout mapping."""
    sd = {}
    idx = 0
    for kind, path, leaves in torch_ingest._flax_slots(
        variables["params"], variables["batch_stats"]
    ):
        prefix = f"m{idx:03d}"
        idx += 1
        if kind == "conv":
            sd[f"{prefix}.weight"] = np.transpose(
                np.asarray(leaves["kernel"]), (3, 2, 0, 1)
            )
            if "bias" in leaves:
                sd[f"{prefix}.bias"] = np.asarray(leaves["bias"])
        elif kind == "linear":
            sd[f"{prefix}.weight"] = np.transpose(np.asarray(leaves["kernel"]))
            sd[f"{prefix}.bias"] = np.asarray(leaves["bias"])
        elif kind == "bn":
            sd[f"{prefix}.weight"] = np.asarray(leaves["scale"])
            sd[f"{prefix}.bias"] = np.asarray(leaves["bias"])
            sd[f"{prefix}.running_mean"] = np.asarray(leaves["mean"])
            sd[f"{prefix}.running_var"] = np.abs(np.asarray(leaves["var"])) + 0.5
            sd[f"{prefix}.num_batches_tracked"] = np.asarray(7)
        else:
            raise AssertionError(f"unexpected slot kind {kind} at {path}")
    return sd


def assert_trees_equal(a, b):
    la = jax.tree_util.tree_leaves_with_path(a)
    lb = jax.tree_util.tree_leaves_with_path(b)
    assert [k for k, _ in la] == [k for k, _ in lb]
    for (k, x), (_, y) in zip(la, lb):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=jax.tree_util.keystr(k)
        )


# ---------------------------------------------------------------------------
# numerics oracles vs torch forward
# ---------------------------------------------------------------------------


def test_convbn_numerics_match_torch():
    """Converted conv+BN weights reproduce torch's eval-mode forward."""
    from distribuuuu_tpu.models.layers import ConvBN

    tconv = torch.nn.Conv2d(3, 8, 3, stride=2, padding=1, bias=False)
    tbn = torch.nn.BatchNorm2d(8)
    with torch.no_grad():
        tbn.weight.copy_(torch.rand(8) + 0.5)
        tbn.bias.copy_(torch.rand(8) - 0.5)
        tbn.running_mean.copy_(torch.rand(8))
        tbn.running_var.copy_(torch.rand(8) + 0.5)
    tconv.eval(), tbn.eval()

    x = np.random.default_rng(0).standard_normal((2, 10, 10, 3)).astype(np.float32)
    with torch.no_grad():
        want = (
            tbn(tconv(torch.from_numpy(np.transpose(x, (0, 3, 1, 2)))))
            .numpy()
            .transpose(0, 2, 3, 1)
        )

    model = ConvBN(8, (3, 3), 2, dtype=jnp.float32)
    variables = model.init(jax.random.key(0), jnp.asarray(x), train=False)
    sd = {
        "conv.weight": tconv.weight.detach().numpy(),
        "bn.weight": tbn.weight.detach().numpy(),
        "bn.bias": tbn.bias.detach().numpy(),
        "bn.running_mean": tbn.running_mean.numpy(),
        "bn.running_var": tbn.running_var.numpy(),
    }
    conv = torch_ingest.convert_state_dict(sd, variables)
    got = model.apply(
        {"params": conv["params"], "batch_stats": conv["batch_stats"]},
        jnp.asarray(x),
        train=False,
    )
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_linear_numerics_match_torch():
    from distribuuuu_tpu.models.layers import Dense

    tfc = torch.nn.Linear(12, 5)
    x = np.random.default_rng(1).standard_normal((3, 12)).astype(np.float32)
    with torch.no_grad():
        want = tfc(torch.from_numpy(x)).numpy()

    model = Dense(5, dtype=jnp.float32)
    variables = model.init(jax.random.key(0), jnp.asarray(x))
    sd = {
        "fc.weight": tfc.weight.detach().numpy(),
        "fc.bias": tfc.bias.detach().numpy(),
    }
    conv = torch_ingest.convert_state_dict(sd, variables)
    got = model.apply({"params": conv["params"]}, jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# full-model round trips (the archs with reference pretrained weights)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("arch", ["resnet18", "resnet50", "densenet121"])
def test_full_model_roundtrip(arch):
    model = models.build_model(arch, num_classes=10, dtype=jnp.float32)
    variables = torch_ingest.ordered_variables(model)
    variables = {
        "params": randomize(variables["params"], seed=3),
        "batch_stats": randomize(variables["batch_stats"], seed=4),
    }
    sd = flax_to_torch_sd(variables)
    conv = torch_ingest.convert_state_dict(sd, variables)
    # abs() in the inverse keeps var positive; compare through the same map
    want_stats = jax.tree.map(np.asarray, variables["batch_stats"])
    for (k, x), (_, y) in zip(
        jax.tree_util.tree_leaves_with_path(conv["batch_stats"]),
        jax.tree_util.tree_leaves_with_path(want_stats),
    ):
        if jax.tree_util.keystr(k).endswith("['var']"):
            continue  # var was abs+0.5'd in the inverse; skip exact check
        np.testing.assert_array_equal(np.asarray(x), y)
    assert_trees_equal(conv["params"], variables["params"])

    # the converted tree must actually run
    out = model.apply(
        {"params": conv["params"], "batch_stats": conv["batch_stats"]},
        jnp.ones((1, 64, 64, 3)),
        train=False,
    )
    assert out.shape == (1, 10)


def test_reference_checkpoint_format_and_module_prefix(tmp_path):
    """torch.save'd reference-style checkpoints ({'state_dict': ...} with DDP
    'module.' prefixes) load through the file path."""
    model = models.build_model("resnet18", num_classes=10, dtype=jnp.float32)
    variables = torch_ingest.ordered_variables(model)
    sd = flax_to_torch_sd(variables)
    wrapped = {
        "epoch": 3,
        "state_dict": {f"module.{k}": torch.from_numpy(np.asarray(v)) for k, v in sd.items()},
    }
    path = str(tmp_path / "ckpt_ep_003.pth.tar")
    torch.save(wrapped, path)

    assert torch_ingest.is_torch_checkpoint(path)
    loaded = torch_ingest.load_torch_state_dict(path)
    assert list(loaded) == list(sd)  # order preserved, prefix stripped
    from flax.linen import meta

    conv = torch_ingest.convert_state_dict(loaded, variables)
    assert_trees_equal(
        conv["params"],
        jax.tree.map(np.asarray, meta.unbox(variables["params"])),
    )


def test_arch_mismatch_raises():
    r18 = models.build_model("resnet18", num_classes=10, dtype=jnp.float32)
    r34 = models.build_model("resnet34", num_classes=10, dtype=jnp.float32)
    sd = flax_to_torch_sd(torch_ingest.ordered_variables(r18))
    with pytest.raises(ValueError):
        torch_ingest.convert_state_dict(sd, torch_ingest.ordered_variables(r34))
