"""Collective micro-benchmarks: the Python sweep runs real collectives on the
fake mesh; the native PJRT tool is built from source and must degrade
gracefully on machines without an attached TPU."""

import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_python_collective_bench_runs():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    out = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "tools", "collective_bench.py"),
            "--max-mb", "0.002", "--iters", "2", "--ops", "psum,ppermute",
        ],
        env=env, capture_output=True, text=True, timeout=400,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "psum" in out.stdout and "ppermute" in out.stdout
    assert "# done" in out.stdout
    # ops filter respected
    assert "all_gather" not in out.stdout


@pytest.fixture(scope="module")
def bench_binary(tmp_path_factory):
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    try:
        import tensorflow  # noqa: F401  — ships the PJRT C API header
    except ImportError:
        pytest.skip("no pjrt_c_api.h source (tensorflow include dir)")
    import tensorflow

    inc = os.path.join(os.path.dirname(tensorflow.__file__), "include")
    if not os.path.isfile(os.path.join(inc, "xla", "pjrt", "c", "pjrt_c_api.h")):
        pytest.skip("pjrt_c_api.h missing from tensorflow include")
    binary = str(tmp_path_factory.mktemp("native") / "collective_bench")
    build = subprocess.run(
        [
            "g++", "-O1", "-std=c++17",
            os.path.join(REPO, "distribuuuu_tpu", "native", "collective_bench.cc"),
            "-o", binary, "-I", inc, "-ldl",
        ],
        capture_output=True, text=True, timeout=300,
    )
    assert build.returncode == 0, build.stderr[-3000:]
    return binary


def test_native_bench_builds_and_fails_gracefully_without_tpu(bench_binary):
    """Missing plugin → exit 2 with a clear message (not a crash)."""
    out = subprocess.run(
        [bench_binary, "--plugin", "/nonexistent/libtpu.so"],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 2
    assert "cannot dlopen" in out.stderr


def test_native_bench_rejects_non_pjrt_plugin(bench_binary):
    """A real .so without GetPjrtApi → exit 2 with a clear message."""
    import ctypes.util

    libm = ctypes.util.find_library("m")
    if libm is None:
        pytest.skip("no libm to use as a decoy")
    out = subprocess.run(
        [bench_binary, "--plugin", libm],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 2
    assert "GetPjrtApi" in out.stderr
