"""Pipeline parallelism: GPipe schedule == sequential execution, fwd + grad.

The SPMD pipeline (parallel/pp.py) must be a pure re-scheduling: outputs and
gradients identical to running the stages back-to-back on one device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distribuuuu_tpu.parallel import mesh as mesh_lib, pp

pytestmark = pytest.mark.slow  # multi-minute on the 1-core CPU mesh

FEAT = 16


def stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def make_params(n_stages, seed=0):
    rng = np.random.default_rng(seed)
    return [
        {
            "w": jnp.asarray(rng.standard_normal((FEAT, FEAT)) * 0.3, jnp.float32),
            "b": jnp.asarray(rng.standard_normal((FEAT,)) * 0.1, jnp.float32),
        }
        for _ in range(n_stages)
    ]


def sequential_apply(param_list, batch):
    x = batch
    for p in param_list:
        x = stage_fn(p, x)
    return x


@pytest.mark.parametrize("data,pipe,micro", [(1, 4, 4), (1, 4, 8), (2, 4, 4)])
def test_pipeline_matches_sequential(data, pipe, micro):
    mesh = mesh_lib.build_mesh(
        data=data, model=1, seq=1, pipe=pipe,
        devices=jax.devices()[: data * pipe],
    )
    param_list = make_params(pipe)
    stacked = pp.stack_stage_params(param_list)
    batch = jnp.asarray(
        np.random.default_rng(1).standard_normal((16, FEAT)), jnp.float32
    )

    apply = pp.pipelined(stage_fn, mesh=mesh, num_microbatches=micro)
    got = jax.jit(apply)(stacked, batch)
    want = sequential_apply(param_list, batch)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_pipeline_gradients_match_sequential():
    pipe, micro = 4, 4
    mesh = mesh_lib.build_mesh(
        data=1, model=1, seq=1, pipe=pipe, devices=jax.devices()[:pipe]
    )
    param_list = make_params(pipe, seed=2)
    stacked = pp.stack_stage_params(param_list)
    batch = jnp.asarray(
        np.random.default_rng(3).standard_normal((8, FEAT)), jnp.float32
    )
    target = jnp.asarray(
        np.random.default_rng(4).standard_normal((8, FEAT)), jnp.float32
    )

    apply = pp.pipelined(stage_fn, mesh=mesh, num_microbatches=micro)

    def pipe_loss(stacked_params):
        return jnp.mean((apply(stacked_params, batch) - target) ** 2)

    def seq_loss(stacked_params):
        param_list = [
            jax.tree.map(lambda x: x[i], stacked_params) for i in range(pipe)
        ]
        return jnp.mean((sequential_apply(param_list, batch) - target) ** 2)

    g_pipe = jax.jit(jax.grad(pipe_loss))(stacked)
    g_seq = jax.jit(jax.grad(seq_loss))(stacked)
    for (k, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(g_pipe),
        jax.tree_util.tree_leaves_with_path(g_seq),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5,
            err_msg=jax.tree_util.keystr(k),
        )


def test_stage_params_sharding_places_stage_dim_on_pipe():
    pipe = 4
    mesh = mesh_lib.build_mesh(
        data=2, model=1, seq=1, pipe=pipe, devices=jax.devices()[: 2 * pipe]
    )
    stacked = pp.stack_stage_params(make_params(pipe))
    shardings = pp.stage_params_sharding(mesh, stacked)
    placed = jax.device_put(stacked, shardings)
    w = placed["w"]  # [4, FEAT, FEAT]
    assert w.sharding.spec[0] == "pipe"
    # each pipe rank holds exactly its stage slice
    shard_shapes = {tuple(s.data.shape) for s in w.addressable_shards}
    assert shard_shapes == {(1, FEAT, FEAT)}
