"""End-to-end trainer tests on the 8-device CPU mesh with dummy data.

The JAX analogue of the reference's only full-path exercise: DummyDataset +
the real train loop (ref: SURVEY.md §4 item 2).
"""

import os

import numpy as np
import pytest

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg

pytestmark = pytest.mark.slow  # multi-minute on the 1-core CPU mesh


def _tiny_cfg(tmp_path, arch="resnet18", max_epoch=1):
    config.reset_cfg()
    cfg.MODEL.ARCH = arch
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.DUMMY_INPUT = True
    cfg.OPTIM.MAX_EPOCH = max_epoch
    cfg.OPTIM.WARMUP_EPOCHS = 1
    cfg.TRAIN.BATCH_SIZE = 2
    cfg.TRAIN.IM_SIZE = 32
    cfg.TRAIN.PRINT_FREQ = 4
    cfg.TEST.BATCH_SIZE = 4
    cfg.TEST.IM_SIZE = 32
    cfg.RNG_SEED = 1
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.OUT_DIR = str(tmp_path)


def test_train_model_end_to_end(tmp_path):
    from distribuuuu_tpu import trainer

    _tiny_cfg(tmp_path)
    # profiler capture rides along: trace steps [1, 3) of epoch 0
    cfg.PROF.ENABLED = True
    cfg.PROF.START_STEP = 1
    cfg.PROF.NUM_STEPS = 2
    best = trainer.train_model()
    # dummy labels are constant → the model should overfit immediately
    assert best > 50.0
    # config provenance dumped (ref: utils.py:56-58)
    assert os.path.isfile(os.path.join(str(tmp_path), "config.yaml"))
    # epoch checkpoint written
    assert os.path.isdir(os.path.join(str(tmp_path), "checkpoints", "ckpt_ep_000"))
    # best checkpoint written
    assert os.path.isdir(os.path.join(str(tmp_path), "checkpoints", "best"))
    # profiler trace captured (jax.profiler writes plugins/profile/<ts>/*)
    prof_dir = os.path.join(str(tmp_path), "profile")
    assert os.path.isdir(prof_dir) and any(os.scandir(prof_dir))


def test_auto_resume_continues_from_last(tmp_path):
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.utils import checkpoint as ckpt

    _tiny_cfg(tmp_path, max_epoch=1)
    trainer.train_model()
    assert ckpt.has_checkpoint()
    assert ckpt.get_last_checkpoint().endswith("ckpt_ep_000")

    # raise MAX_EPOCH and train again: must resume at epoch 1, not redo 0
    _tiny_cfg(tmp_path, max_epoch=2)
    trainer.train_model()
    assert ckpt.get_last_checkpoint().endswith("ckpt_ep_001")


def test_test_model_with_weights(tmp_path):
    from distribuuuu_tpu import trainer

    _tiny_cfg(tmp_path)
    trainer.train_model()
    cfg.MODEL.WEIGHTS = os.path.join(str(tmp_path), "checkpoints", "best")
    top1, topk = trainer.test_model()
    assert top1 > 50.0
    assert topk >= top1


def test_checkpoint_roundtrip_values(tmp_path):
    """Saved arrays must restore bit-exact (ref semantics: utils.py:391-410)."""
    import jax
    import jax.numpy as jnp

    from distribuuuu_tpu.utils import checkpoint as ckpt

    _tiny_cfg(tmp_path)
    tree = {
        "params": {"w": jnp.arange(6, dtype=jnp.float32).reshape(2, 3)},
        "batch_stats": {"m": jnp.ones((3,), jnp.float32)},
        "opt_state": {"mu": jnp.full((2, 3), 0.5, jnp.float32)},
    }
    ckpt.save_checkpoint(tree, epoch=7, best_acc1=12.5, is_best=True)
    restored = ckpt.load_checkpoint(ckpt.get_checkpoint(7))
    assert int(restored["epoch"]) == 7
    assert float(restored["best_acc1"]) == 12.5
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["w"]),
        np.arange(6, dtype=np.float32).reshape(2, 3),
    )
    # best is weights-only
    best = ckpt.load_checkpoint(ckpt.get_best_checkpoint())
    assert "opt_state" not in best and "params" in best
