"""Convergence smoke: the compiled train step actually LEARNS.

The e2e tests use the dummy dataset (constant label 0), which a model can
satisfy through the classifier bias alone. Here labels are a nontrivial
deterministic function of the pixels, so loss can only fall if real feature
learning happens — the offline stand-in for the reference's embedded
convergence transcripts (ref: tutorial/snsc.py:92-111, SURVEY.md §6).
"""

import jax
import jax.numpy as jnp
import numpy as np

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer
from distribuuuu_tpu.parallel import mesh as mesh_lib, sharding as sharding_lib
from distribuuuu_tpu.utils.optim import construct_optimizer

import pytest

pytestmark = pytest.mark.slow  # multi-minute on the 1-core CPU mesh


def synthetic_batch(rng, n):
    images = rng.standard_normal((n, 32, 32, 3)).astype(np.float32)
    labels = ((images.mean(axis=(1, 2, 3)) * 40.0).astype(np.int64) % 10).astype(
        np.int32
    )
    images += labels[:, None, None, None] * 0.1
    return {
        "image": images,
        "label": labels,
        "mask": np.ones((n,), np.float32),
    }


def test_train_step_learns_nontrivial_labels():
    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.OPTIM.BASE_LR = 0.05
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.RNG_SEED = 0

    mesh = mesh_lib.build_mesh()
    model = trainer.build_model_from_cfg()
    state = trainer.create_train_state(model, jax.random.key(0), mesh, 32)
    step = trainer.make_train_step(model, construct_optimizer(), topk=5)

    rng = np.random.default_rng(0)
    losses = []
    for _ in range(40):
        batch = sharding_lib.shard_batch(mesh, synthetic_batch(rng, 64))
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))

    start = np.mean(losses[:5])
    end = np.mean(losses[-5:])
    # chance is ln(10) ≈ 2.30; real learning must at least halve the loss
    assert start > 1.5, f"unexpectedly easy start: {losses[:5]}"
    assert end < start * 0.5, f"no learning: start {start:.3f} → end {end:.3f}"
    assert np.isfinite(losses).all()
