"""`models.register_model` — the documented answer to the reference's timm
fallback (ref: /root/reference/distribuuuu/trainer.py:123-128 falls back to
`timm.create_model` for unknown archs; this zoo is closed + explicitly
extensible instead — VERDICT r1 item 9).

A custom arch registered through the public decorator must work everywhere
an arch name does: the registry, `build_model_from_cfg`, and a real jitted
train step via the YAML-configured trainer path.
"""

from typing import Any

import numpy as np
import flax.linen as nn
import jax
import jax.numpy as jnp
import pytest

from distribuuuu_tpu import models, trainer
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.parallel import mesh as mesh_lib, sharding as sharding_lib
from distribuuuu_tpu.utils.optim import construct_optimizer


class TinyNet(nn.Module):
    """Minimal custom arch: conv → GAP → head. Accepts the trainer's
    standard kwargs (dtype, bn_group) like any zoo arch."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(8, (3, 3), dtype=self.dtype, param_dtype=jnp.float32)(x)
        x = nn.relu(x)
        x = x.mean(axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(
            x.astype(jnp.float32)
        )


@pytest.fixture()
def registered(monkeypatch):
    """Register a custom arch through the REAL public decorator (so a
    regression in register_model itself fails these tests), cleaning the
    registry up afterwards."""
    # ensure cleanup even though registration goes through the decorator
    monkeypatch.delitem(models._REGISTRY, "tiny_custom", raising=False)

    @models.register_model
    def tiny_custom(num_classes=1000, dtype=jnp.float32, bn_group=0, **kw):
        return TinyNet(num_classes=num_classes, dtype=dtype)

    assert models._REGISTRY["tiny_custom"] is tiny_custom  # decorator works
    yield tiny_custom
    models._REGISTRY.pop("tiny_custom", None)


def test_registry_rejects_unknown_arch():
    """VERDICT r4 #8: the closed-zoo error must name the divergence (no
    timm fallback) and point at the extension hook."""
    with pytest.raises(KeyError, match="Unknown arch") as ei:
        models.build_model("definitely_not_registered")
    msg = str(ei.value)
    assert "register_model" in msg
    assert "timm" in msg


def test_registered_arch_builds(registered):
    m = models.build_model("tiny_custom", num_classes=7, dtype=jnp.float32)
    assert isinstance(m, TinyNet) and m.num_classes == 7


def test_registered_arch_trains_via_cfg(registered):
    """The YAML-visible path: MODEL.ARCH names the custom arch and the
    normal trainer machinery runs a step on it."""
    cfg.MODEL.ARCH = "tiny_custom"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    trainer.check_trainer_mesh()
    mesh = mesh_lib.build_mesh()
    model = trainer.build_model_from_cfg()
    state = trainer.create_train_state(model, jax.random.key(0), mesh, 16)
    step = trainer.make_train_step(model, construct_optimizer(), topk=5)
    rng = np.random.default_rng(0)
    batch = sharding_lib.shard_batch(
        mesh,
        {
            "image": rng.standard_normal((16, 16, 16, 3)).astype(np.float32),
            "label": rng.integers(0, 10, size=(16,)).astype(np.int32),
            "mask": np.ones((16,), np.float32),
        },
    )
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_register_model_decorator_is_public():
    assert callable(models.register_model)
    assert "tiny_custom" not in models.available_models()  # fixtures clean up
