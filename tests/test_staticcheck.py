"""The static analysis plane (ISSUE 14): every pass proven LIVE by a
seeded violation — a lint that cannot fire on its own fixture fails CI —
plus the waiver round-trip, the ``--json-out`` schema, the 0-unwaived
AST gate on the real repo, and the committed ANALYSIS_r01.json /
ANALYSIS_BASELINE.json artifact pins.

Program-pass fixtures are TOY programs on the 8-virtual-device mesh
(sub-second compiles), not real stanzas — the full-registry program run
is the committed artifact (regenerate:
``python tools/staticcheck.py --json-out ANALYSIS_r01.json``), pinned
here without recompiling it.
"""

import json
import os
import subprocess
import sys
import textwrap

import jax
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import distribuuuu_tpu.config as config
from distribuuuu_tpu.analysis import hlo, program
from distribuuuu_tpu.analysis.findings import (
    Finding,
    Report,
    finding_key,
    load_baseline,
)
from distribuuuu_tpu.analysis.passes import (
    collectives as collectives_pass,
    dispatch as dispatch_pass,
    donation as donation_pass,
    dtype as dtype_pass,
    knobs as knobs_pass,
    replication as replication_pass,
    telemetry as telemetry_pass,
)
from distribuuuu_tpu.parallel import mesh as mesh_lib
from distribuuuu_tpu.parallel.partition import topology

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-virtual-device mesh"
)


# ---------------------------------------------------------------- helpers

def _mesh(data=8, model=1):
    return mesh_lib.build_mesh(data=data, model=model)


def _toy_bundle(mesh, topo, layout, fn, state_in, batch_in,
                compute_dtype="float32", expectations=None):
    """A ProgramBundle from a toy jitted fn — the seeded-violation rig.
    ``fn(state, batch) -> (state, metrics)`` like the real step."""
    from distribuuuu_tpu.parallel.partition import specs

    lowered = fn.lower(state_in, batch_in)
    compiled = lowered.compile()
    return program.ProgramBundle(
        name="toy", arch="toy", topology=topo, mesh=mesh, layout=layout,
        lowered_text=hlo.stablehlo_with_locs(lowered),
        compiled_text=compiled.as_text(),
        state_in=state_in,
        state_out_shardings=compiled.output_shardings[0],
        n_flat_inputs=len(jax.tree.leaves((state_in, batch_in))),
        memory=None,
        expectations=expectations or specs.collective_expectations(
            layout, topo
        ),
        geometry={"compute_dtype": compute_dtype},
    )


def _sds(shape, dtype, sharding):
    return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)


def _toy_state_cls():
    import flax.struct

    @flax.struct.dataclass
    class ToyState:
        params: dict
        batch_stats: dict
        opt_state: dict
    return ToyState


# ------------------------------------------------- replication (seeded)

def test_replication_pass_fires_on_a_demoted_leaf():
    """Declared P('data') leaf deliberately pinned replicated in-graph:
    the pass must flag it with the uneven-dim arithmetic."""
    ToyState = _toy_state_cls()
    mesh = _mesh()
    topo = topology.Topology(data=8)
    sharded = NamedSharding(mesh, P("data"))
    repl = NamedSharding(mesh, P())
    layout = {k: {"w": sharded} for k in ("params", "opt", "grads")}

    def step(state, batch):
        w = jax.lax.with_sharding_constraint(state.params["w"], repl)
        return (
            state.replace(params={"w": w + batch["x"].sum()}),
            {"loss": batch["x"].sum()},
        )

    state = ToyState(
        params={"w": _sds((16, 4), np.float32, sharded)},
        batch_stats={}, opt_state={},
    )
    batch = {"x": _sds((16,), np.float32, sharded)}
    bundle = _toy_bundle(
        mesh, topo, layout, jax.jit(step), state, batch
    )
    findings = replication_pass.run(bundle)
    assert len(findings) == 1, [f.message for f in findings]
    f = findings[0]
    assert f.pass_id == "replication" and f.severity == "error"
    assert "REPLICATED" in f.message and "16 % 8 = 0" in f.message
    assert f.waiver_key == finding_key("replication", "toy", "w")


def test_replication_pass_quiet_on_agreeing_program():
    ToyState = _toy_state_cls()
    mesh = _mesh()
    topo = topology.Topology(data=8)
    sharded = NamedSharding(mesh, P("data"))
    layout = {k: {"w": sharded} for k in ("params", "opt", "grads")}

    def step(state, batch):
        w = jax.lax.with_sharding_constraint(
            state.params["w"] * 2.0, sharded
        )
        return state.replace(params={"w": w}), {}

    state = ToyState(params={"w": _sds((16, 4), np.float32, sharded)},
                     batch_stats={}, opt_state={})
    batch = {"x": _sds((16,), np.float32, sharded)}
    bundle = _toy_bundle(mesh, topo, layout, jax.jit(step), state, batch)
    assert replication_pass.run(bundle) == []


# ---------------------------------------------------- donation (seeded)

def test_donation_pass_fires_on_undonated_threaded_state():
    """The same threaded-state program jitted WITHOUT donate_argnums:
    the pass reports the doubled-footprint bytes."""
    ToyState = _toy_state_cls()
    mesh = _mesh()
    topo = topology.Topology(data=8)
    sharded = NamedSharding(mesh, P("data"))
    layout = {k: {"w": sharded} for k in ("params", "opt", "grads")}

    def step(state, batch):
        return state.replace(
            params={"w": state.params["w"] + 1.0}
        ), {"loss": batch["x"].sum()}

    state = ToyState(params={"w": _sds((64, 8), np.float32, sharded)},
                     batch_stats={}, opt_state={})
    batch = {"x": _sds((16,), np.float32, sharded)}

    undonated = _toy_bundle(
        mesh, topo, layout, jax.jit(step), state, batch
    )
    findings = donation_pass.run(undonated)
    assert len(findings) == 1
    assert "NOT aliased" in findings[0].message or \
        "NO input/output aliasing" in findings[0].message
    assert str(64 * 8 * 4) in findings[0].message  # the w bytes

    donated = _toy_bundle(
        mesh, topo, layout, jax.jit(step, donate_argnums=0), state, batch
    )
    assert donation_pass.run(donated) == []


# -------------------------------------------------- collectives (seeded)

def test_collective_pass_fires_on_gather_in_ddp_program():
    """An explicit sharded→replicated→sharded round-trip in a zero=0
    program = an all-gather over data the spec algebra predicts none of."""
    ToyState = _toy_state_cls()
    mesh = _mesh()
    topo = topology.Topology(data=8)
    sharded = NamedSharding(mesh, P("data"))
    repl = NamedSharding(mesh, P())
    layout = {k: {"w": sharded} for k in ("params", "opt", "grads")}

    def step(state, batch):
        gathered = jax.lax.with_sharding_constraint(
            state.params["w"], repl
        )
        w = jax.lax.with_sharding_constraint(gathered * 2.0, sharded)
        return state.replace(params={"w": w}), {}

    state = ToyState(params={"w": _sds((64, 8), np.float32, sharded)},
                     batch_stats={}, opt_state={})
    batch = {"x": _sds((16,), np.float32, sharded)}
    bundle = _toy_bundle(
        mesh, topo, layout, jax.jit(step, donate_argnums=0), state, batch
    )
    findings = collectives_pass.run(bundle)
    assert any(
        f.pass_id == "collectives" and "all-gather" in f.message
        and "data" in f.message
        for f in findings
    ), [f.message for f in findings]
    # the ledger records the census even for clean programs
    assert "collective_ledger" in bundle.extras


def test_collective_census_attributes_axes():
    """The replica-group decoder handles both HLO spellings and maps
    groups onto mesh axes."""
    assert hlo.decode_replica_groups(
        "replica_groups={{0,2,4,6},{1,3,5,7}}"
    ) == [[0, 2, 4, 6], [1, 3, 5, 7]]
    assert hlo.decode_replica_groups(
        "replica_groups=[2,4]<=[4,2]T(1,0)"
    ) == [[0, 2, 4, 6], [1, 3, 5, 7]]
    assert hlo.decode_replica_groups(
        "replica_groups=[4,2]<=[8]"
    ) == [[0, 1], [2, 3], [4, 5], [6, 7]]
    mesh = _mesh(data=4, model=2)
    table = hlo.mesh_axis_groups(mesh)
    assert hlo.attribute_groups(
        [[0, 2, 4, 6], [1, 3, 5, 7]], table
    ) == ("data",)
    assert hlo.attribute_groups(
        [[0, 1], [2, 3], [4, 5], [6, 7]], table
    ) == ("model",)
    assert hlo.attribute_groups([list(range(8))], table) == (
        "data", "model",
    )


# -------------------------------------------------------- dtype (seeded)

def test_dtype_pass_fires_on_stray_upcast():
    """A bf16 intermediate upcast to f32 in plain model code (no safe
    scope) must be flagged; the BN-style safe scope must not."""
    ToyState = _toy_state_cls()
    mesh = _mesh()
    topo = topology.Topology(data=8)
    sharded = NamedSharding(mesh, P("data"))
    layout = {k: {"w": sharded} for k in ("params", "opt", "grads")}

    def step(state, batch):
        import jax.numpy as jnp

        h = batch["x"].astype(jnp.bfloat16) * 2.0
        with jax.named_scope("middle_block"):
            leak = h.astype(jnp.float32) * 3.0  # the seeded leak
        with jax.named_scope("BatchNorm_stats"):
            safe = h.astype(jnp.float32).var()  # safe scope
        w = state.params["w"] + leak.sum() + safe
        return state.replace(params={"w": w}), {}

    state = ToyState(params={"w": _sds((64, 8), np.float32, sharded)},
                     batch_stats={}, opt_state={})
    batch = {"x": _sds((16, 8), np.float32, sharded)}
    bundle = _toy_bundle(
        mesh, topo, layout, jax.jit(step, donate_argnums=0), state, batch,
        compute_dtype="bfloat16",
    )
    findings = dtype_pass.run(bundle)
    assert len(findings) == 1, [f.message for f in findings]
    assert "middle_block" in findings[0].message
    assert bundle.extras["upcasts"]["total"] >= 2
    assert bundle.extras["upcasts"]["unsafe"] == 1

    bundle.geometry["compute_dtype"] = "float32"
    assert dtype_pass.run(bundle) == []  # f32 programs: nothing to audit


# -------------------------------------------------------- knobs (seeded)

def _knob_fixture(tmp_path, extra_read="", extra_decl=""):
    pkg = tmp_path / "distribuuuu_tpu"
    pkg.mkdir()
    (pkg / "config.py").write_text(textwrap.dedent("""
        _C = CfgNode()
        _C.TRAIN = CfgNode()
        _C.TRAIN.BATCH_SIZE = 32
        _C.TRAIN.DEAD_KNOB = 1
    """) + extra_decl)
    (pkg / "user.py").write_text(textwrap.dedent("""
        from distribuuuu_tpu.config import cfg
        def f():
            return cfg.TRAIN.BATCH_SIZE
    """) + extra_read)
    (tmp_path / "README.md").write_text(
        "`TRAIN.BATCH_SIZE` and `TRAIN.DEAD_KNOB` and the stale "
        "`TRAIN.RENAMED_AWAY` knob.\n"
    )
    (tmp_path / "docs").mkdir()
    return str(tmp_path)

def test_knobs_pass_fires_in_all_directions(tmp_path):
    root = _knob_fixture(
        tmp_path,
        extra_read="def g():\n    return cfg.TRAIN.NOT_DECLARED\n",
    )
    findings = knobs_pass.run(root)
    by_key = {f.waiver_key: f for f in findings}
    assert finding_key("knobs", "undeclared", "TRAIN.NOT_DECLARED") in by_key
    assert finding_key("knobs", "dead", "TRAIN.DEAD_KNOB") in by_key
    assert finding_key("knobs", "stale-doc", "TRAIN.RENAMED_AWAY") in by_key
    # the documented+read knob raises nothing
    assert not any("BATCH_SIZE" in k for k in by_key)


def test_knobs_section_escape_suppresses_dead(tmp_path):
    """A bare section read (aliased away) makes its children reachable —
    the pass must NOT cry dead on them."""
    root = _knob_fixture(
        tmp_path,
        extra_read="def h(validate):\n    return validate(cfg.TRAIN)\n",
    )
    findings = knobs_pass.run(root)
    assert not any(
        f.waiver_key == finding_key("knobs", "dead", "TRAIN.DEAD_KNOB")
        for f in findings
    )


# ------------------------------------------------------ dispatch (seeded)

def test_dispatch_pass_fires_on_offring_thread_dispatch(tmp_path):
    pkg = tmp_path / "distribuuuu_tpu" / "asyncplane"
    pkg.mkdir(parents=True)
    (pkg / "rogue.py").write_text(textwrap.dedent("""
        import threading
        import jax
        from distribuuuu_tpu.asyncplane import sequencer

        def _worker(state):
            jax.block_until_ready(state)          # OFF-RING: finding
            sequencer.dispatch("eval", jax.block_until_ready, state)  # ok

        def _helper(x):
            jax.device_put(x)                     # reached from _worker2

        def _worker2(x):
            _helper(x)

        def start(state):
            threading.Thread(target=_worker, args=(state,)).start()
            threading.Thread(target=_worker2, args=(state,)).start()
            jax.block_until_ready(state)          # main thread: NOT flagged
    """))
    findings = dispatch_pass.run(str(tmp_path))
    keys = {f.waiver_key for f in findings}
    assert finding_key(
        "dispatch", "distribuuuu_tpu/asyncplane/rogue.py", "_worker",
        "jax.block_until_ready",
    ) in keys
    assert finding_key(
        "dispatch", "distribuuuu_tpu/asyncplane/rogue.py", "_helper",
        "jax.device_put",
    ) in keys
    assert len(findings) == 2  # wrapped + main-thread sites stay clean


def test_dispatch_pass_clean_on_repo():
    """The shipped async plane is ring-disciplined (the PR 11 invariant,
    now held by a lint instead of memory)."""
    assert dispatch_pass.run(REPO) == []


# ----------------------------------------------------- telemetry (seeded)

def test_telemetry_pass_and_wrapper_compat(tmp_path):
    bad = tmp_path / "pkg"
    bad.mkdir()
    (bad / "mod.py").write_text(
        "from distribuuuu_tpu.utils.jsonlog import metrics_log\n"
        "metrics_log('totally_new_kind', x=1)\n"
    )
    findings, seen = telemetry_pass.check_tree(str(bad))
    assert len(findings) == 1 and findings[0].pass_id == "telemetry"
    assert "undeclared kind" in findings[0].message
    # the wrapper keeps the historical (violations, seen) string API
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import check_telemetry_schema as chk
    finally:
        sys.path.pop(0)
    violations, seen2 = chk.check_tree(str(bad))
    assert violations and isinstance(violations[0], str)
    assert "undeclared kind 'totally_new_kind'" in violations[0]
    assert seen == seen2 == {"totally_new_kind"}


# ------------------------------------------------- waivers / report / CLI

def test_waiver_round_trip_and_stale_detection(tmp_path):
    f1 = Finding("knobs", "warning", "config.py::X.Y", "dead",
                 finding_key("knobs", "dead", "X.Y"))
    report = Report()
    report.extend([f1])
    baseline = {
        "schema": 1,
        "waivers": [
            {"key": f1.waiver_key, "justification": "load-bearing",
             "date": "2026-08-05"},
            {"key": "knobs::dead::GONE", "justification": "old",
             "date": "2026-01-01"},
        ],
    }
    report.apply_baseline(baseline)
    assert f1.waived and len(report.unwaived) == 1
    stale = report.unwaived[0]
    assert stale.pass_id == "baseline" and "stale waiver" in stale.message
    # partial runs don't judge staleness
    r2 = Report()
    r2.extend([Finding("knobs", "warning", "l", "m", f1.waiver_key)])
    r2.apply_baseline(baseline, check_stale=False)
    assert r2.unwaived == []


def test_baseline_refuses_unjustified_waiver(tmp_path):
    path = tmp_path / "BASELINE.json"
    path.write_text(json.dumps(
        {"schema": 1, "waivers": [{"key": "a::b", "date": "2026-08-05"}]}
    ))
    with pytest.raises(ValueError, match="justification"):
        load_baseline(str(path))


def test_cli_ast_only_json_out_schema(tmp_path):
    """The CLI's --ast-only run over the REAL repo: exit 0 (the 0-unwaived
    gate on AST passes) and a schema-complete --json-out."""
    out_path = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, os.path.join("tools", "staticcheck.py"),
         "--ast-only", "--json-out", str(out_path)],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    doc = json.loads(out_path.read_text())
    assert doc["schema"] == 1
    assert doc["n_unwaived"] == 0
    assert {"knobs", "dispatch", "telemetry"} <= set(doc["passes_run"])
    for f in doc["findings"]:
        assert {"pass_id", "severity", "location", "message",
                "waiver_key", "waived"} <= set(f)


# ----------------------------------------------------- committed artifacts

def _artifact():
    path = os.path.join(REPO, "ANALYSIS_r01.json")
    assert os.path.exists(path), (
        "ANALYSIS_r01.json missing — regenerate: "
        "python tools/staticcheck.py --json-out ANALYSIS_r01.json"
    )
    with open(path) as f:
        return json.load(f)


def test_artifact_covers_registry_at_zero_unwaived():
    """The committed full-registry report: every shipped model YAML and
    every core sweep case analyzed, 0 unwaived findings."""
    import glob as globlib

    import yaml

    doc = _artifact()
    assert doc["n_unwaived"] == 0, [
        f["waiver_key"] for f in doc["findings"] if not f["waived"]
    ]
    case_names = {c["name"] for c in doc["cases"]}
    for path in sorted(globlib.glob(os.path.join(REPO, "config", "*.yaml"))):
        with open(path) as f:
            if "MODEL" not in (yaml.safe_load(f) or {}):
                continue
        assert f"config/{os.path.basename(path)}" in case_names, path
    assert all(c["ok"] for c in doc["cases"]), [
        c["name"] for c in doc["cases"] if not c["ok"]
    ]
    # the generated core sweep cases are in there too
    assert sum(1 for n in case_names if n.startswith("sweep/")) >= 5
    # program passes all ran
    assert {"replication", "donation", "collectives", "dtype"} <= set(
        doc["passes_run"]
    )
    # per-case collective ledger present (ROADMAP #1's referee artifact)
    assert any(c.get("collective_ledger") for c in doc["cases"])


def test_baseline_waivers_regeneration_pinned():
    """Every committed waiver is justified+dated AND still matched by a
    finding in the committed report (no silent rot in either direction
    — the artifact's own stale check ran at 0 unwaived)."""
    baseline = load_baseline(os.path.join(REPO, "ANALYSIS_BASELINE.json"))
    doc = _artifact()
    report_keys = {f["waiver_key"] for f in doc["findings"]}
    for w in baseline["waivers"]:
        assert w["key"] in report_keys, (
            f"waiver {w['key']} matches no finding in ANALYSIS_r01.json "
            "— stale; regenerate both"
        )
    waived_keys = {f["waiver_key"] for f in doc["findings"] if f["waived"]}
    assert waived_keys == {w["key"] for w in baseline["waivers"]}


def test_live_ast_passes_match_committed_artifact():
    """The AST half re-runs live (seconds) and must agree with the
    committed artifact: same unwaived count (0) against the committed
    baseline — catching source drift between regenerations."""
    from distribuuuu_tpu.analysis import runner

    config.reset_cfg()
    report = runner.run_all(repo=REPO, ast_only=True)
    assert [f.waiver_key for f in report.unwaived] == [], [
        (f.waiver_key, f.message) for f in report.unwaived
    ]
