"""Serving subsystem (distribuuuu_tpu/serve/): bucketed-shape padding
correctness, flush-on-timeout vs flush-on-full, backpressure at MAX_QUEUE,
graceful drain, steady-state zero-recompilation, and end-to-end
serve-vs-``test_model``-logits equality on a tiny arch (fast tier, CPU).
"""

from __future__ import annotations

import io
import json
import os
import signal
import socket
import threading
import time

import jax
import numpy as np
import pytest

import distribuuuu_tpu.config as config
from distribuuuu_tpu import trainer
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.parallel import mesh as mesh_lib
from distribuuuu_tpu.serve import (
    AdmissionController,
    Engine,
    EngineClosedError,
    QueueFullError,
    ServeMetrics,
    default_buckets,
)
from distribuuuu_tpu.serve import engine as engine_lib
from distribuuuu_tpu.serve import protocol

IM = 16
NC = 10


def _tiny_cfg():
    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = NC
    cfg.MODEL.BN_GROUP = 8
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.TRAIN.IM_SIZE = IM
    cfg.TEST.IM_SIZE = IM


@pytest.fixture(scope="module")
def served():
    """One tiny model + eval variables for every engine in this module."""
    _tiny_cfg()
    mesh = mesh_lib.build_mesh(data=1, model=1, seq=1, pipe=1,
                               devices=[jax.devices()[0]])
    model = trainer.build_model_from_cfg()
    state = trainer.create_train_state(model, jax.random.key(0), mesh, IM)
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    return model, variables


@pytest.fixture(scope="module")
def engine(served):
    """The shared float32 engine (buckets 1/2/4). Tests that drain or need
    special geometry build their own."""
    model, variables = served
    eng = Engine(
        model, variables, IM,
        max_batch=4, max_wait_ms=250.0, max_queue=32,
        input_dtype=np.float32,
    )
    eng.start()
    yield eng
    eng.drain()


def _float_images(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, IM, IM, 3)).astype(np.float32)


def test_default_buckets():
    assert default_buckets(8) == [1, 2, 4, 8]
    assert default_buckets(6) == [1, 2, 4, 6]
    assert default_buckets(1) == [1]
    with pytest.raises(ValueError):
        default_buckets(0)


def test_bucket_validation(served):
    model, variables = served
    with pytest.raises(ValueError, match="BUCKET_SIZES"):
        Engine(model, variables, IM, max_batch=4, bucket_sizes=[1, 2],
               input_dtype=np.float32)  # missing MAX_BATCH bucket


def test_admission_controller_unit():
    adm = AdmissionController(max_queue=2)
    adm.admit(0, 5.0)
    adm.admit(1, 5.0)
    with pytest.raises(QueueFullError) as ei:
        adm.admit(2, 7.5)
    assert ei.value.retry_after_ms == 7.5
    assert ei.value.max_queue == 2
    adm.close()
    with pytest.raises(EngineClosedError):
        adm.admit(0, 5.0)


def test_submit_validates_shape_and_dtype(engine):
    with pytest.raises(ValueError, match="compiled input"):
        engine.submit(np.zeros((IM, IM, 3), np.uint8))  # wrong dtype
    with pytest.raises(ValueError, match="compiled input"):
        engine.submit(np.zeros((IM + 1, IM, 3), np.float32))  # wrong shape


def test_padded_logits_masked_and_match_eval(served, engine):
    """A 3-request flush pads to bucket 4: responses must be bitwise
    independent of the padding rows and numerically identical to the eval
    forward ``test_model`` runs on the same inputs."""
    model, variables = served
    images = _float_images(3, seed=1)

    futs = [engine.submit(img) for img in images]
    got = np.stack([f.result() for f in futs])

    # (a) identity with the eval-step forward at the natural (unpadded)
    # batch shape — the exact apply() validate()/test_model() computes
    ref = np.asarray(
        jax.jit(lambda v, x: model.apply(v, x, train=False))(variables, images)
    )
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)

    # (b) padding rows cannot contaminate real rows: run the engine's own
    # bucket-4 executable with zero padding vs garbage padding — the first
    # three rows must be BITWISE equal
    pad_zero = np.zeros((4, IM, IM, 3), np.float32)
    pad_zero[:3] = images
    pad_garbage = pad_zero.copy()
    pad_garbage[3] = 1e6
    out_zero = np.asarray(engine._compiled[4](variables, pad_zero))
    out_garbage = np.asarray(engine._compiled[4](variables, pad_garbage))
    assert (out_zero[:3] == out_garbage[:3]).all()
    # and the engine's demuxed responses are those same rows
    assert (got == out_zero[:3]).all()


def test_flush_on_full_vs_flush_on_timeout(engine):
    # full: MAX_BATCH requests flush immediately, far under MAX_WAIT_MS
    engine.metrics = ServeMetrics()
    t0 = time.perf_counter()
    futs = [engine.submit(img) for img in _float_images(4, seed=2)]
    for f in futs:
        f.result()
    full_elapsed = time.perf_counter() - t0
    assert full_elapsed < 0.2, f"flush-on-full waited {full_elapsed:.3f}s"
    snap = engine.metrics.snapshot()
    assert snap["batches"] == 1 and snap["batch_occupancy"] == 1.0

    # timeout: a partial batch waits out MAX_WAIT_MS then flushes padded
    engine.metrics = ServeMetrics()
    t0 = time.perf_counter()
    futs = [engine.submit(img) for img in _float_images(3, seed=3)]
    for f in futs:
        f.result()
    partial_elapsed = time.perf_counter() - t0
    assert partial_elapsed >= 0.2, (
        f"partial batch flushed after {partial_elapsed:.3f}s — "
        "before the 250 ms window"
    )
    snap = engine.metrics.snapshot()
    assert snap["batches"] == 1
    assert snap["batch_occupancy"] == pytest.approx(3 / 4)


def test_backpressure_rejects_at_max_queue(served):
    """With the batcher not yet running, the queue fills to MAX_QUEUE and
    the next submit is rejected with a retry-after hint; starting the
    engine then serves everything that was admitted."""
    model, variables = served
    eng = Engine(
        model, variables, IM, max_batch=1, max_wait_ms=1.0, max_queue=4,
        input_dtype=np.float32,
    )
    images = _float_images(5, seed=4)
    futs = [eng.submit(img) for img in images[:4]]
    with pytest.raises(QueueFullError) as ei:
        eng.submit(images[4])
    assert ei.value.retry_after_ms > 0
    assert ei.value.depth == 4
    eng.start()
    for f in futs:
        assert f.result().shape == (NC,)
    eng.drain()


def test_graceful_drain_completes_inflight(served):
    model, variables = served
    eng = Engine(
        model, variables, IM, max_batch=2, max_wait_ms=500.0, max_queue=32,
        input_dtype=np.float32,
    )
    eng.start()
    futs = [eng.submit(img) for img in _float_images(5, seed=5)]
    eng.drain()  # must flush the partial tail immediately, not after 500 ms
    for f in futs:
        assert f.result().shape == (NC,)
    with pytest.raises(EngineClosedError):
        eng.submit(_float_images(1, seed=6)[0])
    assert eng.metrics.snapshot()["requests"] == 5


def test_drain_before_start_fails_pending(served):
    model, variables = served
    eng = Engine(model, variables, IM, max_batch=1, max_wait_ms=1.0,
                 input_dtype=np.float32)
    fut = eng.submit(_float_images(1, seed=7)[0])
    eng.drain()
    with pytest.raises(EngineClosedError):
        fut.result(timeout=1)


def test_sigterm_drain_flag():
    """The serve loop's SIGTERM handling follows the preempt pattern:
    handler sets a flag, the accept loop polls it."""
    from distribuuuu_tpu.serve import drain_requested, install_drain, reset_drain

    reset_drain()
    assert not drain_requested()
    install_drain(signals=(signal.SIGUSR1,))
    os.kill(os.getpid(), signal.SIGUSR1)
    deadline = time.time() + 2
    while not drain_requested() and time.time() < deadline:
        time.sleep(0.01)
    assert drain_requested()
    reset_drain()


def test_steady_state_never_recompiles(engine):
    """Startup compiles exactly the configured buckets (the
    compilation-count hook); mixed-size steady-state traffic adds zero."""
    assert engine.n_compiles == len(engine.buckets) == 3
    events_before = len(engine_lib.COMPILE_EVENTS)
    for n in (1, 4, 3, 2, 4, 1, 3):
        futs = [engine.submit(img) for img in _float_images(n, seed=10 + n)]
        for f in futs:
            f.result()
    assert engine.n_compiles == 3
    assert len(engine_lib.COMPILE_EVENTS) == events_before
    assert set(engine._compiled) == {1, 2, 4}


def test_run_batch_roundtrip(served, engine, tmp_path):
    """Batch mode: npy in → logits npy out, equal to the direct eval
    forward; N above MAX_QUEUE exercises the retry/backoff path."""
    model, variables = served
    images = _float_images(6, seed=8)
    src, dst = tmp_path / "in.npy", tmp_path / "out.npy"
    np.save(src, images)
    n = protocol.run_batch(engine, str(src), str(dst))
    assert n == 6
    out = np.load(dst)
    ref = np.asarray(
        jax.jit(lambda v, x: model.apply(v, x, train=False))(variables, images)
    )
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_socket_roundtrip(served):
    """Length-prefixed socket frontend end-to-end: uint8 npy request in,
    JSON logits out, numerically matching the eval forward (uint8 inputs
    take the in-graph normalize path — DATA.DEVICE_NORMALIZE serving)."""
    _tiny_cfg()  # protocol.make_transform reads cfg (IM_SIZEs, normalize)
    model, variables = served
    eng = Engine(
        model, variables, IM, max_batch=2, max_wait_ms=5.0, max_queue=16,
        input_dtype=np.uint8,
    )
    eng.start()
    listener = protocol.open_listener("127.0.0.1", 0)
    port = listener.getsockname()[1]
    stop = threading.Event()
    server = threading.Thread(
        target=protocol.serve_forever,
        args=(eng, listener, stop.is_set),
        kwargs=dict(topk=3, poll_s=0.05),
        daemon=True,
    )
    server.start()
    try:
        img = np.random.default_rng(9).integers(
            0, 256, (IM, IM, 3), dtype=np.uint8
        )
        buf = io.BytesIO()
        np.save(buf, img)
        with socket.create_connection(("127.0.0.1", port), timeout=10) as conn:
            protocol.send_frame(conn, buf.getvalue())
            resp = json.loads(protocol.recv_frame(conn))
            # malformed payload → per-request error, connection survives
            protocol.send_frame(conn, b"not an image")
            err = json.loads(protocol.recv_frame(conn))
    finally:
        stop.set()
        server.join(timeout=10)
    assert "error" not in resp, resp
    assert len(resp["logits"]) == NC
    assert resp["topk"][0] == resp["pred"]
    from distribuuuu_tpu.data.transforms import normalize_in_graph

    ref = np.asarray(
        jax.jit(
            lambda v, x: model.apply(v, normalize_in_graph(x), train=False)
        )(variables, img[None])
    )[0]
    np.testing.assert_allclose(resp["logits"], ref, rtol=1e-5, atol=1e-5)
    assert resp["pred"] == int(np.argmax(ref))
    assert "error" in err
    # serve_forever drained the engine on stop
    with pytest.raises(EngineClosedError):
        eng.submit(img)
