"""Model zoo tests: param-count oracles and forward shapes.

Param counts are checked against the published torch numbers — the survey's
checkable oracle (SURVEY.md §7 hard part 4; reference table README.md:206-217
for the archs the baselines cover).
"""

import jax
import jax.numpy as jnp
import pytest

from distribuuuu_tpu.models import available_models, build_model

# arch -> M params (torch/torchvision + reference README published values)
PARAM_ORACLE = {
    "resnet18": 11.690,
    "resnet34": 21.798,
    "resnet50": 25.557,
    "resnet101": 44.549,
    "resnet152": 60.193,
    "resnext50_32x4d": 25.029,
    "resnext101_32x8d": 88.791,
    "wide_resnet50_2": 68.883,
    "wide_resnet101_2": 126.887,
}


def _count_params(model, im_size=224):
    shapes = jax.eval_shape(
        lambda k: model.init(k, jnp.ones((1, im_size, im_size, 3)), train=False),
        jax.random.key(0),
    )
    return sum(
        int(jnp.prod(jnp.asarray(x.shape))) for x in jax.tree.leaves(shapes["params"])
    )


@pytest.mark.parametrize("arch", sorted(PARAM_ORACLE))
def test_param_count_matches_torch(arch):
    n = _count_params(build_model(arch)) / 1e6
    assert n == pytest.approx(PARAM_ORACLE[arch], abs=5e-4), f"{arch}: {n:.3f}M"


def test_unknown_arch_raises_with_listing():
    with pytest.raises(KeyError, match="resnet18"):
        build_model("not_a_model")


def test_resnet18_forward_shapes_and_stats():
    model = build_model("resnet18", num_classes=10)
    x = jnp.ones((2, 64, 64, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    assert "params" in variables and "batch_stats" in variables
    # eval path: running stats, no mutation
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    # train path mutates batch_stats
    logits, mutated = model.apply(
        variables, x, train=True, mutable=["batch_stats"]
    )
    assert logits.shape == (2, 10)
    leaves_before = jax.tree.leaves(variables["batch_stats"])
    leaves_after = jax.tree.leaves(mutated["batch_stats"])
    assert any(
        not jnp.allclose(a, b) for a, b in zip(leaves_before, leaves_after)
    ), "train=True must update running stats"


def test_num_classes_plumbs_through():
    model = build_model("resnet18", num_classes=7)
    x = jnp.ones((1, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    assert model.apply(variables, x, train=False).shape == (1, 7)


def test_registry_covers_reference_resnets():
    for arch in PARAM_ORACLE:
        assert arch in available_models()
