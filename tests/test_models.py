"""Model zoo tests: param-count oracles and forward shapes.

Param counts are checked against the published torch numbers — the survey's
checkable oracle (SURVEY.md §7 hard part 4; reference table README.md:206-217
for the archs the baselines cover).
"""

import jax
import jax.numpy as jnp
import pytest

from distribuuuu_tpu.models import available_models, build_model

pytestmark = pytest.mark.slow  # multi-minute on the 1-core CPU mesh

# arch -> M params (torch/torchvision + reference README published values;
# the timm-sourced archs use the reference baseline table README.md:206-217)
PARAM_ORACLE = {
    "resnet18": 11.690,
    "resnet34": 21.798,
    "resnet50": 25.557,
    "resnet101": 44.549,
    "resnet152": 60.193,
    "resnext50_32x4d": 25.029,
    "resnext101_32x8d": 88.791,
    "wide_resnet50_2": 68.883,
    "wide_resnet101_2": 126.887,
    "densenet121": 7.979,
    "densenet161": 28.681,
    "densenet169": 14.149,
    "densenet201": 20.014,
    "botnet50": 20.859,
    "efficientnet_b0": 5.289,
    "regnetx_160": 54.279,
    "regnety_160": 83.590,
    "regnety_320": 145.047,
}


def _count_params(model, im_size=224):
    shapes = jax.eval_shape(
        lambda k: model.init(k, jnp.ones((1, im_size, im_size, 3)), train=False),
        jax.random.key(0),
    )
    return sum(
        int(jnp.prod(jnp.asarray(x.shape))) for x in jax.tree.leaves(shapes["params"])
    )


@pytest.mark.parametrize("arch", sorted(PARAM_ORACLE))
def test_param_count_matches_torch(arch):
    n = _count_params(build_model(arch)) / 1e6
    assert n == pytest.approx(PARAM_ORACLE[arch], abs=5e-4), f"{arch}: {n:.3f}M"


def test_unknown_arch_raises_with_listing():
    with pytest.raises(KeyError, match="resnet18"):
        build_model("not_a_model")


def test_resnet18_forward_shapes_and_stats():
    model = build_model("resnet18", num_classes=10)
    x = jnp.ones((2, 64, 64, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    assert "params" in variables and "batch_stats" in variables
    # eval path: running stats, no mutation
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32
    # train path mutates batch_stats
    logits, mutated = model.apply(
        variables, x, train=True, mutable=["batch_stats"]
    )
    assert logits.shape == (2, 10)
    leaves_before = jax.tree.leaves(variables["batch_stats"])
    leaves_after = jax.tree.leaves(mutated["batch_stats"])
    assert any(
        not jnp.allclose(a, b) for a, b in zip(leaves_before, leaves_after)
    ), "train=True must update running stats"


def test_num_classes_plumbs_through():
    model = build_model("resnet18", num_classes=7)
    x = jnp.ones((1, 32, 32, 3))
    variables = model.init(jax.random.key(0), x, train=False)
    assert model.apply(variables, x, train=False).shape == (1, 7)


def test_registry_covers_reference_resnets():
    for arch in PARAM_ORACLE:
        assert arch in available_models()


@pytest.mark.parametrize(
    "arch,kwargs",
    [
        ("densenet121", {}),
        ("regnety_160", {}),
        ("efficientnet_b0", {}),
        ("botnet50", {"fmap_size": (2, 2)}),
    ],
)
def test_family_forward_shapes(arch, kwargs):
    """Every model family runs forward (train + eval) at a small size."""
    model = build_model(arch, num_classes=10, **kwargs)
    x = jnp.ones((2, 32, 32, 3))
    variables = model.init(
        {"params": jax.random.key(0), "dropout": jax.random.key(1)}, x, train=False
    )
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    logits, _ = model.apply(
        variables, x, train=True, mutable=["batch_stats"],
        rngs={"dropout": jax.random.key(2)},
    )
    assert logits.shape == (2, 10)


def test_densenet_memory_efficient_grads_match():
    """remat (≙ torch.utils.checkpoint, ref densenet.py:81-86) must not
    change values or gradients."""
    x = jnp.ones((2, 32, 32, 3))

    def make(mem_eff):
        m = build_model("densenet121", num_classes=5, memory_efficient=mem_eff)
        v = m.init(jax.random.key(0), x, train=False)
        return m, v

    m0, v0 = make(False)
    m1, v1 = make(True)

    def loss(m, v):
        def f(params):
            out, _ = m.apply(
                {"params": params, "batch_stats": v["batch_stats"]},
                x, train=True, mutable=["batch_stats"],
            )
            return (out ** 2).mean()

        return jax.value_and_grad(f)(v["params"])

    l0, g0 = loss(m0, v0)
    l1, g1 = loss(m1, v1)
    assert float(l0) == pytest.approx(float(l1), rel=1e-5)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        assert jnp.allclose(a, b, rtol=1e-4, atol=1e-6)
