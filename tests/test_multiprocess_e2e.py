"""Multi-PROCESS end-to-end training — the framework's DDP-equivalent path.

Everything else in tests/ exercises multi-device single-process. This spawns
2 OS processes (each with 4 virtual CPU devices) that rendezvous through the
torch-launcher-style env contract (MASTER_ADDR/WORLD_SIZE/RANK →
``parallel.mesh.setup_distributed``), train the same tiny model on dummy
data, validate, and write one collective orbax checkpoint — the reference's
"multi-node without a cluster" exercise (ref: README.md:119-144) done with
processes instead of GPU partitions.
"""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("DTPU_TEST_NDEV", "4")
).strip()
import jax
jax.config.update("jax_platforms", "cpu")

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer

out_dir = sys.argv[1]
config.reset_cfg()
cfg.MODEL.ARCH = "resnet18"
cfg.MODEL.NUM_CLASSES = 10
cfg.MODEL.DUMMY_INPUT = True
cfg.OPTIM.MAX_EPOCH = 1
cfg.TRAIN.BATCH_SIZE = 2
cfg.TRAIN.IM_SIZE = 32
cfg.TRAIN.PRINT_FREQ = 8
cfg.TEST.BATCH_SIZE = 4
cfg.TEST.IM_SIZE = 32
cfg.RNG_SEED = 1
cfg.DEVICE.COMPUTE_DTYPE = "float32"
cfg.OUT_DIR = out_dir
if len(sys.argv) > 2:
    cfg.merge_from_list(sys.argv[2:])  # KEY VALUE ... overrides, CLI-style
best = trainer.train_model()
from distribuuuu_tpu.parallel import mesh as mesh_lib
dg_rank, dg_world = mesh_lib.data_process_groups()
print(f"WORKER_RESULT rank={jax.process_index()} nproc={jax.process_count()} "
      f"ndev={jax.device_count()} dg={dg_rank}/{dg_world} best={best:.3f}",
      flush=True)
"""


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_group(tmp_path, script, script_args, nprocs, ndev, log_name):
    """The one launcher env/Popen contract (torch-launcher-style env →
    setup_distributed): every multi-process test goes through here.
    Worker output goes to files, not pipes: a full 64KB pipe would block
    a rank mid-collective and deadlock the group."""
    port = _free_port()  # avoid collisions with concurrent runs
    procs, logs = [], []
    for rank in range(nprocs):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.update(
            MASTER_ADDR="127.0.0.1",
            COORDINATOR_PORT=str(port),
            WORLD_SIZE=str(nprocs),
            RANK=str(rank),
            DTPU_TEST_NDEV=str(ndev),
            # the worker script lives in tmp_path, so the repo root is not
            # on its sys.path (script dir ≠ cwd); put the package in reach
            PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        log = open(tmp_path / log_name(rank, port), "w+")
        logs.append(log)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script), *script_args],
                env=env, stdout=log, stderr=subprocess.STDOUT,
                text=True, cwd=REPO,
            )
        )
    return procs, logs


def _spawn_workers(tmp_path, extra_args=(), nprocs=2, ndev=4, run_tag=""):
    """Spawn ``nprocs`` workers (each a JAX process with ``ndev`` virtual
    CPU devices) and return their collected outputs."""
    out_dir = str(tmp_path / "run")
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    procs, logs = _launch_group(
        tmp_path, script, (out_dir, *extra_args), nprocs, ndev,
        lambda rank, port: f"rank{rank}{run_tag}.log",
    )
    outs = []
    for p, log in zip(procs, logs):
        p.wait(timeout=900)
        log.seek(0)
        outs.append(log.read())
        log.close()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
    return out_dir, outs


def _check_results(outs, nprocs=2, ndev=4):
    results = {}
    for out in outs:
        m = re.search(
            r"WORKER_RESULT rank=(\d) nproc=(\d) ndev=(\d+) "
            r"dg=(\d+)/(\d+) best=([\d.]+)", out
        )
        assert m, out[-2000:]
        results[int(m.group(1))] = {
            "nproc": int(m.group(2)), "ndev": int(m.group(3)),
            "dg": int(m.group(4)), "dg_world": int(m.group(5)),
            "best": float(m.group(6)),
        }
    assert set(results) == set(range(nprocs))
    for r in results.values():
        assert r["nproc"] == nprocs
        assert r["ndev"] == nprocs * ndev  # global device view
    # the validation metric is a global reduction — identical on all ranks
    assert len({r["best"] for r in results.values()}) == 1
    # constant dummy labels → immediate overfit, same bar as single-process
    assert results[0]["best"] > 50.0
    return results


def _run_two_process(tmp_path, extra_args=()):
    out_dir, outs = _spawn_workers(tmp_path, extra_args)
    _check_results(outs)
    # one collective checkpoint, written once
    ckpt_dir = os.path.join(out_dir, "checkpoints")
    assert sorted(os.listdir(ckpt_dir)) == ["best", "ckpt_ep_000"]


@pytest.mark.slow
def test_two_process_training(tmp_path):
    """DP across the process boundary (the reference's DDP topology)."""
    _run_two_process(tmp_path)


WORKER_PREEMPT = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count="
    + os.environ.get("DTPU_TEST_NDEV", "4")
).strip()
import jax
jax.config.update("jax_platforms", "cpu")

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer

out_dir, tree = sys.argv[1], sys.argv[2]
config.reset_cfg()
cfg.MODEL.ARCH = "resnet18"
cfg.MODEL.NUM_CLASSES = 2
cfg.MODEL.SYNCBN = True
cfg.TRAIN.DATASET = tree
cfg.TEST.DATASET = tree
cfg.TRAIN.IM_SIZE = 32
cfg.TEST.IM_SIZE = 48
cfg.TRAIN.BATCH_SIZE = 2   # per chip; 256 imgs / (2·ndev·nprocs) = 16 b/ep for both drill geometries
cfg.TEST.BATCH_SIZE = 4
cfg.TRAIN.WORKERS = 2
cfg.TRAIN.PRINT_FREQ = 1   # log every batch: the parent triggers on these
cfg.TRAIN.PREEMPT_SAVE = True
cfg.OPTIM.MAX_EPOCH = 2
cfg.OPTIM.BASE_LR = 0.0125
cfg.OPTIM.WARMUP_EPOCHS = 0
cfg.DATA.BACKEND = "pil"
cfg.RNG_SEED = 1
cfg.DEVICE.COMPUTE_DTYPE = "float32"
cfg.OUT_DIR = out_dir
best = trainer.train_model()
print(f"WORKER_DONE rank={jax.process_index()} best={best}", flush=True)
"""


def _preempt_drill(tmp_path, nprocs, ndev):
    """SIGTERM exactly ONE of ``nprocs`` processes mid-epoch: the
    cross-process flag agreement (utils/preempt.requested_global's
    process_allgather branch) must bring EVERY rank to the collective
    preempt save — one ``preempt_ep_*`` checkpoint, no hang — and an
    ``nprocs``-process resume must complete the run and prune the preempt
    checkpoint (VERDICT r2 #4). The only tests where the every-8th-window
    multi-host throttle (trainer.train_epoch) executes with real
    processes. Geometry: per-host batch 2×ndev; nprocs×ndev devices ⇒
    256 imgs / (2·ndev·nprocs) batches per epoch — callers keep this at
    16 so the kill window and the batch-8 agreement site line up."""
    import signal
    import time

    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.make_imagefolder import make_tree

    tree = make_tree(
        str(tmp_path / "tree"), n_classes=2, train_per_class=128,
        val_per_class=8, min_size=48, max_size=64, seed=5,
    )
    out_dir = str(tmp_path / "run")
    script = tmp_path / "worker.py"
    script.write_text(WORKER_PREEMPT)
    ckpt_dir = os.path.join(out_dir, "checkpoints")

    def spawn():
        return _launch_group(
            tmp_path, script, (out_dir, tree), nprocs, ndev,
            lambda rank, port: f"p{rank}_{port}.log",
        )

    def finish(procs, logs):
        outs = []
        for p, log in zip(procs, logs):
            p.wait(timeout=900)
            log.seek(0)
            outs.append(log.read())
            log.close()
        for rank, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
        return outs

    # ---- run 1: SIGTERM rank 0 only, once it is visibly mid-epoch ----
    procs, logs = spawn()
    deadline = time.time() + 600
    sent = False
    while time.time() < deadline:
        logs[0].flush()
        with open(logs[0].name) as f:
            txt = f.read()
        # batch ≥ 2 of epoch 1 printed → mid-epoch, well before the
        # every-8th-batch agreement site at batch 8 (of 16)
        if re.search(r"Epoch\[1/2\]\[ *[2-7]/16\]", txt):
            procs[0].send_signal(signal.SIGTERM)
            sent = True
            break
        if procs[0].poll() is not None:
            break
        time.sleep(1.0)
    assert sent, "never saw a mid-epoch train window in rank0's log"
    outs = finish(procs, logs)
    assert "preemption signaled" in outs[0], outs[0][-2000:]
    # both ranks reached the collective save: exactly one preempt ckpt,
    # no epoch checkpoint yet
    entries = sorted(os.listdir(ckpt_dir))
    assert entries == ["preempt_ep_000"], entries

    # ---- run 2: clean resume from the preempt checkpoint ----
    procs, logs = spawn()
    outs = finish(procs, logs)
    for out in outs:
        assert "WORKER_DONE" in out, out[-2000:]
    assert re.search(r"resumed from .*preempt_ep_000", outs[0]), outs[0][-2000:]
    entries = sorted(os.listdir(ckpt_dir))
    assert entries == ["best", "ckpt_ep_000", "ckpt_ep_001"], entries


@pytest.mark.slow
def test_two_process_preemption_drill(tmp_path):
    _preempt_drill(tmp_path, nprocs=2, ndev=4)


@pytest.mark.slow
def test_four_process_preemption_drill(tmp_path):
    """4-way agreement: one SIGTERM among 4 ranks must still converge all
    four to the same collective save (r5 — the 2-process drill cannot
    distinguish pairwise agreement from group agreement)."""
    _preempt_drill(tmp_path, nprocs=4, ndev=2)


@pytest.mark.slow
def test_two_process_tensor_parallel(tmp_path):
    """DP×TP with the model axis alive across 2 processes (data=4 ×
    model=2 over 8 global devices): TP's GSPMD collectives ride the
    distributed backend, not just local devices."""
    _run_two_process(tmp_path, ("MESH.MODEL", "2"))


@pytest.mark.slow
def test_two_process_expert_parallel(tmp_path):
    """DP×EP: vit_tiny_moe with expert tensors sharded over a model axis
    that spans the process boundary — the expert-partials psum is a real
    cross-process collective."""
    _run_two_process(tmp_path, ("MODEL.ARCH", "vit_tiny_moe", "MESH.MODEL", "2"))


@pytest.mark.slow
def test_four_process_2x2_mesh(tmp_path):
    """VERDICT r4 #5: 4 OS processes × 1 device each → a 2×2 (data×model)
    mesh in which BOTH axes cross process boundaries — grad psum over a
    2-process data axis and TP collectives over a 2-process model axis in
    the same step. The previous ceiling was 2 processes."""
    out_dir, outs = _spawn_workers(
        tmp_path, ("MESH.MODEL", "2"), nprocs=4, ndev=1
    )
    _check_results(outs, nprocs=4, ndev=1)
    ckpt_dir = os.path.join(out_dir, "checkpoints")
    assert sorted(os.listdir(ckpt_dir)) == ["best", "ckpt_ep_000"]


@pytest.mark.slow
def test_eight_process_2x2x2_mesh(tmp_path):
    """VERDICT r5 item 7: data×model×pipe = 2×2×2 over 8 REAL OS
    processes (1 device each) — every mesh axis crosses process
    boundaries at once: grad psum over a 2-process data axis, TP
    collectives over a 2-process model axis, and the GPipe stage ppermute
    over a 2-process pipe axis, in the same step. Asserts data-group
    sampler placement: the 8 processes must partition into exactly 2 data
    groups of 4 (the model×pipe copies of each data row load IDENTICAL
    batches — parallel/mesh.data_process_groups), and the globally
    reduced eval metric must agree everywhere."""
    out_dir, outs = _spawn_workers(
        tmp_path,
        ("MODEL.ARCH", "vit_tiny", "MESH.DATA", "2", "MESH.MODEL", "2",
         "MESH.PIPE", "2", "TRAIN.BATCH_SIZE", "4"),
        nprocs=8, ndev=1,
    )
    results = _check_results(outs, nprocs=8, ndev=1)
    groups: dict = {}
    for rank, r in results.items():
        assert r["dg_world"] == 2, r
        groups.setdefault(r["dg"], []).append(rank)
    assert sorted(len(v) for v in groups.values()) == [4, 4], groups
    ckpt_dir = os.path.join(out_dir, "checkpoints")
    assert sorted(os.listdir(ckpt_dir)) == ["best", "ckpt_ep_000"]


@pytest.mark.slow
def test_two_process_zero1_resume(tmp_path):
    """VERDICT r4 #5: multi-process ZeRO-1 resume. Run 1 trains one epoch
    with the optimizer state SHARDED over a data axis that spans both
    processes and writes a collective checkpoint (each process writes its
    own opt-state shards through pack_opt_state). Run 2 must reassemble
    the packed optimizer state through the real auto-resume path — a
    fresh-optimizer fallback (the r4 silent-momentum-loss bug class) or a
    shard-placement failure would surface in the logs / crash."""
    zero_args = ("MESH.ZERO", "1", "OPTIM.MAX_EPOCH", "1")
    out_dir, outs = _spawn_workers(tmp_path, zero_args, run_tag="_a")
    _check_results(outs)

    # run 2: two more epochs, resuming from the ZeRO-sharded checkpoint
    _, outs = _spawn_workers(
        tmp_path, ("MESH.ZERO", "1", "OPTIM.MAX_EPOCH", "3"), run_tag="_b"
    )
    for out in outs:
        assert "WORKER_RESULT" in out, out[-2000:]
    assert re.search(r"resumed from .*ckpt_ep_000 \(epoch 1\)", outs[0]), (
        outs[0][-2000:]
    )
    # the graceful weights-only fallback must NOT have fired on any rank
    for rank, out in enumerate(outs):
        assert "optimizer state not restored" not in out, (rank, out[-2000:])
    ckpt_dir = os.path.join(out_dir, "checkpoints")
    assert sorted(os.listdir(ckpt_dir)) == [
        "best", "ckpt_ep_000", "ckpt_ep_001", "ckpt_ep_002",
    ]
