"""Multi-PROCESS end-to-end training — the framework's DDP-equivalent path.

Everything else in tests/ exercises multi-device single-process. This spawns
2 OS processes (each with 4 virtual CPU devices) that rendezvous through the
torch-launcher-style env contract (MASTER_ADDR/WORLD_SIZE/RANK →
``parallel.mesh.setup_distributed``), train the same tiny model on dummy
data, validate, and write one collective orbax checkpoint — the reference's
"multi-node without a cluster" exercise (ref: README.md:119-144) done with
processes instead of GPU partitions.
"""

import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
).strip()
import jax
jax.config.update("jax_platforms", "cpu")

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer

out_dir = sys.argv[1]
arch = sys.argv[2] if len(sys.argv) > 2 else "resnet18"
model_axis = int(sys.argv[3]) if len(sys.argv) > 3 else 1
config.reset_cfg()
cfg.MODEL.ARCH = arch
cfg.MESH.MODEL = model_axis
cfg.MODEL.NUM_CLASSES = 10
cfg.MODEL.DUMMY_INPUT = True
cfg.OPTIM.MAX_EPOCH = 1
cfg.TRAIN.BATCH_SIZE = 2
cfg.TRAIN.IM_SIZE = 32
cfg.TRAIN.PRINT_FREQ = 8
cfg.TEST.BATCH_SIZE = 4
cfg.TEST.IM_SIZE = 32
cfg.RNG_SEED = 1
cfg.DEVICE.COMPUTE_DTYPE = "float32"
cfg.OUT_DIR = out_dir
best = trainer.train_model()
print(f"WORKER_RESULT rank={jax.process_index()} nproc={jax.process_count()} "
      f"ndev={jax.device_count()} best={best:.3f}", flush=True)
"""


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_two_process(tmp_path, extra_args=()):
    out_dir = str(tmp_path / "run")
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    port = _free_port()  # avoid collisions with concurrent runs

    # Worker output goes to files, not pipes: a full 64KB pipe would block a
    # rank mid-collective and deadlock the pair.
    procs, logs = [], []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("JAX_PLATFORMS", None)
        env.update(
            MASTER_ADDR="127.0.0.1",
            COORDINATOR_PORT=str(port),
            WORLD_SIZE="2",
            RANK=str(rank),
            # the worker script lives in tmp_path, so the repo root is not
            # on its sys.path (script dir ≠ cwd); put the package in reach
            PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
        )
        log = open(tmp_path / f"rank{rank}.log", "w+")
        logs.append(log)
        procs.append(
            subprocess.Popen(
                [sys.executable, str(script), out_dir, *extra_args],
                env=env, stdout=log, stderr=subprocess.STDOUT,
                text=True, cwd=REPO,
            )
        )
    outs = []
    for p, log in zip(procs, logs):
        p.wait(timeout=900)
        log.seek(0)
        outs.append(log.read())
        log.close()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"

    results = {}
    for out in outs:
        m = re.search(
            r"WORKER_RESULT rank=(\d) nproc=(\d) ndev=(\d+) best=([\d.]+)", out
        )
        assert m, out[-2000:]
        results[int(m.group(1))] = m
    assert set(results) == {0, 1}
    for m in results.values():
        assert m.group(2) == "2"   # both saw 2 processes
        assert m.group(3) == "8"   # global device view: 2 hosts × 4 chips
    # the validation metric is a global reduction — identical on both ranks
    assert results[0].group(4) == results[1].group(4)
    # constant dummy labels → immediate overfit, same bar as single-process
    assert float(results[0].group(4)) > 50.0

    # one collective checkpoint, written once
    ckpt_dir = os.path.join(out_dir, "checkpoints")
    assert sorted(os.listdir(ckpt_dir)) == ["best", "ckpt_ep_000"]


@pytest.mark.slow
def test_two_process_training(tmp_path):
    """DP across the process boundary (the reference's DDP topology)."""
    _run_two_process(tmp_path)


WORKER_PREEMPT = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
).strip()
import jax
jax.config.update("jax_platforms", "cpu")

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer

out_dir, tree = sys.argv[1], sys.argv[2]
config.reset_cfg()
cfg.MODEL.ARCH = "resnet18"
cfg.MODEL.NUM_CLASSES = 2
cfg.MODEL.SYNCBN = True
cfg.TRAIN.DATASET = tree
cfg.TEST.DATASET = tree
cfg.TRAIN.IM_SIZE = 32
cfg.TEST.IM_SIZE = 48
cfg.TRAIN.BATCH_SIZE = 2   # ×4 devices = 8/host; 256 imgs / 2 procs → 16 b/ep
cfg.TEST.BATCH_SIZE = 4
cfg.TRAIN.WORKERS = 2
cfg.TRAIN.PRINT_FREQ = 1   # log every batch: the parent triggers on these
cfg.TRAIN.PREEMPT_SAVE = True
cfg.OPTIM.MAX_EPOCH = 2
cfg.OPTIM.BASE_LR = 0.0125
cfg.OPTIM.WARMUP_EPOCHS = 0
cfg.DATA.BACKEND = "pil"
cfg.RNG_SEED = 1
cfg.DEVICE.COMPUTE_DTYPE = "float32"
cfg.OUT_DIR = out_dir
best = trainer.train_model()
print(f"WORKER_DONE rank={jax.process_index()} best={best}", flush=True)
"""


@pytest.mark.slow
def test_two_process_preemption_drill(tmp_path):
    """SIGTERM exactly ONE of 2 processes mid-epoch: the cross-process flag
    agreement (utils/preempt.requested_global's process_allgather branch)
    must bring BOTH ranks to the collective preempt save — one
    ``preempt_ep_*`` checkpoint, no hang — and a 2-process resume must
    complete the run and prune the preempt checkpoint (VERDICT r2 #4).
    This is the only test where the every-8th-window multi-host throttle
    (trainer.train_epoch) executes with real processes."""
    import signal
    import time

    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    from tools.make_imagefolder import make_tree

    tree = make_tree(
        str(tmp_path / "tree"), n_classes=2, train_per_class=128,
        val_per_class=8, min_size=48, max_size=64, seed=5,
    )
    out_dir = str(tmp_path / "run")
    script = tmp_path / "worker.py"
    script.write_text(WORKER_PREEMPT)
    ckpt_dir = os.path.join(out_dir, "checkpoints")

    def spawn():
        port = _free_port()
        procs, logs = [], []
        for rank in range(2):
            env = dict(os.environ)
            env.pop("JAX_PLATFORMS", None)
            env.update(
                MASTER_ADDR="127.0.0.1",
                COORDINATOR_PORT=str(port),
                WORLD_SIZE="2",
                RANK=str(rank),
                PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
            )
            log = open(tmp_path / f"p{rank}_{port}.log", "w+")
            logs.append(log)
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(script), out_dir, tree],
                    env=env, stdout=log, stderr=subprocess.STDOUT,
                    text=True, cwd=REPO,
                )
            )
        return procs, logs

    def finish(procs, logs):
        outs = []
        for p, log in zip(procs, logs):
            p.wait(timeout=900)
            log.seek(0)
            outs.append(log.read())
            log.close()
        for rank, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
        return outs

    # ---- run 1: SIGTERM rank 0 only, once it is visibly mid-epoch ----
    procs, logs = spawn()
    deadline = time.time() + 600
    sent = False
    while time.time() < deadline:
        logs[0].flush()
        with open(logs[0].name) as f:
            txt = f.read()
        # batch ≥ 2 of epoch 1 printed → mid-epoch, well before the
        # every-8th-batch agreement site at batch 8 (of 16)
        if re.search(r"Epoch\[1/2\]\[ *[2-7]/16\]", txt):
            procs[0].send_signal(signal.SIGTERM)
            sent = True
            break
        if procs[0].poll() is not None:
            break
        time.sleep(1.0)
    assert sent, "never saw a mid-epoch train window in rank0's log"
    outs = finish(procs, logs)
    assert "preemption signaled" in outs[0], outs[0][-2000:]
    # both ranks reached the collective save: exactly one preempt ckpt,
    # no epoch checkpoint yet
    entries = sorted(os.listdir(ckpt_dir))
    assert entries == ["preempt_ep_000"], entries

    # ---- run 2: clean resume from the preempt checkpoint ----
    procs, logs = spawn()
    outs = finish(procs, logs)
    for out in outs:
        assert "WORKER_DONE" in out, out[-2000:]
    assert re.search(r"resumed from .*preempt_ep_000", outs[0]), outs[0][-2000:]
    entries = sorted(os.listdir(ckpt_dir))
    assert entries == ["best", "ckpt_ep_000", "ckpt_ep_001"], entries


@pytest.mark.slow
def test_two_process_tensor_parallel(tmp_path):
    """DP×TP with the model axis alive across 2 processes (data=4 ×
    model=2 over 8 global devices): TP's GSPMD collectives ride the
    distributed backend, not just local devices."""
    _run_two_process(tmp_path, ("resnet18", "2"))


@pytest.mark.slow
def test_two_process_expert_parallel(tmp_path):
    """DP×EP: vit_tiny_moe with expert tensors sharded over a model axis
    that spans the process boundary — the expert-partials psum is a real
    cross-process collective."""
    _run_two_process(tmp_path, ("vit_tiny_moe", "2"))
