"""Fused Pallas attention vs the XLA reference path (interpret mode on CPU).

Numerics contract: mhsa_2d_fused must match ops.attention.mhsa_2d — the
BoTNet MHSA math (ref: /root/reference/distribuuuu/models/botnet.py:193-214)
— for forward and gradients, including the 196-token (non-128-aligned) grid.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distribuuuu_tpu.ops import attention as att_ops, pallas_attention


def _inputs(b=2, n=4, length=196, d=32, seed=0):
    rng = np.random.default_rng(seed)
    q, k, v = (
        jnp.asarray(rng.standard_normal((b, n, length, d)).astype(np.float32))
        for _ in range(3)
    )
    pos = jnp.asarray(
        rng.standard_normal((b, n, length, length)).astype(np.float32)
    )
    return q, k, v, pos


@pytest.mark.parametrize("length", [196, 128, 64])
def test_fused_matches_xla(length):
    q, k, v, pos = _inputs(length=length)
    scale = q.shape[-1] ** -0.5
    want = att_ops.mhsa_2d(q, k, v, pos, scale)
    got = pallas_attention.fused_attention(q, k, v, pos, scale, True)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
    )


def test_fused_gradients_match():
    q, k, v, pos = _inputs(length=64, seed=1)
    scale = q.shape[-1] ** -0.5

    def loss_ref(q, k, v, pos):
        return (att_ops.mhsa_2d(q, k, v, pos, scale) ** 2).sum()

    def loss_fused(q, k, v, pos):
        return (pallas_attention.fused_attention(q, k, v, pos, scale, True) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(q, k, v, pos)
    g_fused = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(q, k, v, pos)
    for a, b in zip(g_fused, g_ref):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
        )


@pytest.mark.slow  # dominates the fast tier; full tier covers it
def test_botnet_forward_with_pallas_impl():
    from distribuuuu_tpu import models

    model = models.build_model(
        "botnet50", num_classes=10, dtype=jnp.float32, attn_impl="pallas"
    )
    x = jnp.ones((1, 64, 64, 3), jnp.float32)  # fmap 4x4
    model = models.build_model(
        "botnet50", num_classes=10, dtype=jnp.float32, attn_impl="pallas",
        fmap_size=(4, 4),
    )
    variables = model.init(jax.random.key(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (1, 10)
    ref_model = models.build_model(
        "botnet50", num_classes=10, dtype=jnp.float32, attn_impl="xla",
        fmap_size=(4, 4),
    )
    ref_out = ref_model.apply(variables, x, train=False)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref_out), rtol=1e-4, atol=1e-4
    )


def test_use_pallas_resolution():
    assert pallas_attention.use_pallas("pallas") is True
    assert pallas_attention.use_pallas("xla") is False
    assert pallas_attention.use_pallas("auto") == (
        jax.default_backend() == "tpu"
    )
