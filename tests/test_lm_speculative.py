"""Speculative + tensor-parallel + sampled generation (ISSUE 17): the
sampling suite's replay invariant, speculative decoding pinned against
target-only decode and against the host acceptance-rule reference, TP
decode pinned against the single-device path, and the config/telemetry/
artifact satellites."""

import glob
import json
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.lm import generate as G


def _tiny_gpt(seq_len=32, vocab=320, dtype=jnp.float32, **kw):
    from distribuuuu_tpu.models.gpt import GPT

    return GPT(
        vocab_size=vocab, seq_len=seq_len, dim=32, depth=2, num_heads=2,
        dtype=dtype, **kw,
    )


def _params(model, key=0):
    return model.init(
        jax.random.key(key), model.dummy_input(), train=False
    )["params"]


def _engine(model, params, **kw):
    kw.setdefault("prompt_len", 8)
    kw.setdefault("max_new_tokens", 6)
    kw.setdefault("batch_tiles", [2])
    kw.setdefault("cache_tiles", [16])
    return G.GenerateEngine(model, {"params": params}, **kw)


@pytest.fixture()
def f32(monkeypatch):
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    yield


# ------------------------------------------------------- host references
#
# Both references run the ENGINE's own selection math (warp_probs /
# _uniform / _pick, the per-stream draw counters) over teacher-forced
# model.apply logits — so a mismatch means the engine's scheduling or
# acceptance logic drifted, not float noise in a reimplementation.


def _tf_row(model, variables, toks):
    """Next-token logits after the token list ``toks`` (teacher-forced)."""
    lg = model.apply(
        variables, jnp.asarray(np.asarray(toks, np.int32)[None]),
        train=False,
    )
    return np.asarray(lg)[0, -1]


def _host_stream(model, variables, prompt, max_new, sp, eos_id, cache_cap):
    """Target-only decode reference: greedy argmax or counter-uniform
    sampled selection, with the engine's retire rules."""
    hist = [int(t) for t in prompt]
    length = len(hist)
    draws = [0, 0, 0, 0]

    def select(row, stream=G._U_PLAIN):
        if sp.greedy:
            return int(np.asarray(row).argmax())
        u = G._uniform(sp.seed, stream, draws[stream])
        draws[stream] += 1
        return G._pick(G.warp_probs(row, sp), u)

    out = [select(_tf_row(model, variables, hist))]
    hist.append(out[0])
    finished = (out[0] == eos_id or len(out) >= max_new
                or length + 1 >= cache_cap)
    while not finished:
        tok = select(_tf_row(model, variables, hist))
        out.append(tok)
        hist.append(tok)
        length += 1
        finished = (tok == eos_id or len(out) >= max_new
                    or length + 1 >= cache_cap)
    return out


def _host_spec_stream(target, tvars, draft, dvars, prompt, max_new, k, sp,
                      eos_id, cache_cap):
    """The acceptance-rule reference (ISSUE 17c): draft proposes K from
    its warped distribution, target verifies teacher-forced, accept iff
    u*q(d) <= p(d), rejected positions resample from max(p-q, 0), all-K
    rounds take the bonus token — same draw-counter bookkeeping as the
    engine, so sampled streams must match token for token."""
    hist = [int(t) for t in prompt]
    length = len(hist)
    draws = [0, 0, 0, 0]
    out = []

    def select(row, stream=G._U_PLAIN):
        if sp.greedy:
            return int(np.asarray(row).argmax())
        u = G._uniform(sp.seed, stream, draws[stream])
        draws[stream] += 1
        return G._pick(G.warp_probs(row, sp), u)

    def emit(tok):
        nonlocal length
        out.append(tok)
        hist.append(tok)
        length += 1
        return (tok == eos_id or len(out) >= max_new
                or length + 1 >= cache_cap)

    first = select(_tf_row(target, tvars, hist))
    out.append(first)
    hist.append(first)
    finished = (first == eos_id or len(out) >= max_new
                or length + 1 >= cache_cap)
    while not finished:
        props, qrows, ctx = [], [], list(hist)
        for _ in range(k):
            row = _tf_row(draft, dvars, ctx)
            d = select(row, G._U_DRAFT)
            props.append(d)
            qrows.append(row)
            ctx.append(d)
        lg = np.asarray(target.apply(
            tvars, jnp.asarray(np.asarray(hist + props, np.int32)[None]),
            train=False,
        ))[0]
        vrows = lg[len(hist) - 1: len(hist) + k]
        broke = False
        for j in range(k):
            d, trow = props[j], vrows[j]
            if sp.greedy:
                tgt = int(trow.argmax())
                if d == tgt:
                    if emit(d):
                        finished = broke = True
                        break
                    continue
                finished = emit(tgt)
                broke = True
                break
            p = G.warp_probs(trow, sp)
            q = G.warp_probs(qrows[j], sp)
            u = G._uniform(sp.seed, G._U_ACCEPT, draws[G._U_ACCEPT])
            draws[G._U_ACCEPT] += 1
            if u * q[d] <= p[d]:
                if emit(d):
                    finished = broke = True
                    break
                continue
            r = np.maximum(p - q, 0.0)
            if r.sum() <= 0.0:
                r = p
            u = G._uniform(sp.seed, G._U_RESID, draws[G._U_RESID])
            draws[G._U_RESID] += 1
            finished = emit(G._pick(r, u))
            broke = True
            break
        if not broke:
            finished = emit(select(vrows[k]))
    return out


# ------------------------------------------------------ sampling (17b)


def test_sample_cfg_validation(f32):
    with pytest.raises(ValueError, match=r"TEMPERATURE=-0.5 must be >= 0"):
        G.validate_sample_cfg(-0.5, 0, 1.0)
    with pytest.raises(ValueError, match=r"TOP_K=-1 must be >= 0"):
        G.validate_sample_cfg(1.0, -1, 1.0)
    with pytest.raises(ValueError, match=r"TOP_P=0.0 must lie in \(0, 1\]"):
        G.validate_sample_cfg(1.0, 0, 0.0)
    with pytest.raises(ValueError, match=r"TOP_P=1.5"):
        G.validate_sample_cfg(1.0, 0, 1.5)
    # ctrl-frame dict overlays the GENERATE.SAMPLE defaults
    cfg.GENERATE.SAMPLE.TEMPERATURE = 0.7
    cfg.GENERATE.SAMPLE.SEED = 11
    sp = G.sample_params({"top_k": 5})
    assert (sp.temperature, sp.top_k, sp.top_p, sp.seed) == (0.7, 5, 1.0, 11)
    assert not sp.greedy and G.sample_params(None).greedy is False
    assert G.sample_params({"temperature": 0.0}).greedy


def test_warp_probs_and_pick_math(f32):
    rng = np.random.default_rng(7)
    logits = rng.standard_normal(32)
    # temperature-only == softmax(logits / T)
    sp = G.SampleParams(temperature=0.8)
    x = logits / 0.8
    ref = np.exp(x - x.max())
    ref /= ref.sum()
    np.testing.assert_allclose(G.warp_probs(logits, sp), ref, atol=1e-12)
    # top-k keeps exactly the k largest (distinct logits here)
    p = G.warp_probs(logits, G.SampleParams(temperature=1.0, top_k=4))
    assert (p > 0).sum() == 4
    assert set(np.flatnonzero(p)) == set(np.argsort(-logits)[:4])
    # top-p keeps the minimal probability-sorted prefix with mass >= P
    sp = G.SampleParams(temperature=1.0, top_p=0.6)
    p = G.warp_probs(logits, sp)
    base = G.warp_probs(logits, G.SampleParams(temperature=1.0))
    kept = np.flatnonzero(p)
    order = np.argsort(-base, kind="stable")
    cut = len(kept)
    assert base[order[:cut]].sum() >= 0.6 > base[order[:cut - 1]].sum()
    np.testing.assert_allclose(p.sum(), 1.0, atol=1e-12)
    # inverse-CDF picks the bucket containing u; greedy ignores u
    probs = np.array([0.2, 0.5, 0.3])
    assert G._pick(probs, 0.1) == 0
    assert G._pick(probs, 0.3) == 1
    assert G._pick(probs, 0.95) == 2
    assert G._pick(probs, 0.9999999) == 2
    assert G.sample_token(logits, G.SampleParams()) == int(logits.argmax())
    # counter-based uniform: pure function of (seed, stream, n)
    assert G._uniform(3, 1, 5) == G._uniform(3, 1, 5)
    assert G._uniform(3, 1, 5) != G._uniform(3, 2, 5)


@pytest.mark.slow  # tier-1 budget: heavy pin, slow tier (ISSUE 17 sat. 5)
def test_sampled_stream_replay_and_host_reference(f32):
    """Same seed ⇒ bit-identical stream across engine instances AND under
    concurrent batching; the stream equals the host reference computed
    with the module's own selection math; a different seed diverges."""
    model = _tiny_gpt(seq_len=32)
    params = _params(model)
    prompt = np.asarray([5, 9, 2, 11], np.int32)
    sample = {"temperature": 0.9, "top_k": 12, "top_p": 0.95, "seed": 42}

    def run(decoys=0):
        eng = _engine(model, params, batch_tiles=[1, 2],
                      cache_tiles=[32], max_new_tokens=10).start()
        subs = [
            eng.submit([7, 3], max_new_tokens=10,
                       sample={"temperature": 1.0, "seed": 1000 + i})
            for i in range(decoys)
        ]
        got = eng.submit(prompt, max_new_tokens=10, sample=sample).result()
        for s in subs:
            s.result()
        eng.drain()
        return got

    solo = run()
    assert run() == solo                       # replay across instances
    assert run(decoys=1) == solo               # batching-independent
    ref = _host_stream(
        model, {"params": params}, prompt, 10,
        G.SampleParams(0.9, 12, 0.95, 42), eng_eos := 256, 32,
    )
    assert solo == ref
    other = _host_stream(
        model, {"params": params}, prompt, 10,
        G.SampleParams(0.9, 12, 0.95, 43), eng_eos, 32,
    )
    assert solo != other                       # the seed is load-bearing


# ---------------------------------------------------- speculative (17c)


def test_speculate_cfg_validation(f32):
    target = _tiny_gpt(seq_len=32)
    draft = _tiny_gpt(seq_len=32)
    with pytest.raises(ValueError, match=r"SPECULATE.K=0 must be >= 1"):
        G.validate_speculate_cfg(0, target, draft, 8, 6, [16])
    small = _tiny_gpt(seq_len=32, vocab=64)
    with pytest.raises(
        ValueError,
        match=r"draft vocab_size=64 != target vocab_size=320",
    ):
        G.validate_speculate_cfg(4, target, small, 8, 6, [16])
    # cache-tile headroom: K extra rows, the exact sum in-message
    with pytest.raises(
        ValueError,
        match=r"PROMPT_LEN=8 \+ MAX_NEW_TOKENS=6 \+ SPECULATE.K=4 = 18",
    ):
        G.validate_speculate_cfg(4, target, draft, 8, 6, [16])
    short = _tiny_gpt(seq_len=16)
    with pytest.raises(
        ValueError, match=r"exceeds the draft model's trained context",
    ):
        G.validate_speculate_cfg(4, target, short, 8, 6, [32])
    G.validate_speculate_cfg(4, target, draft, 8, 6, [32])  # headroom ok


def test_speculative_greedy_identical_to_target_only(f32):
    """THE 17c pin: greedy speculative output is token-identical to
    target-only decode for an arbitrary (random, disagreeing) draft —
    speedup may vary, the stream may not."""
    model = _tiny_gpt(seq_len=32)
    params = _params(model, key=0)
    draft = _tiny_gpt(seq_len=32)
    dparams = _params(draft, key=1)  # independent init: a BAD draft
    base = _engine(model, params, batch_tiles=[1, 2], cache_tiles=[32],
                   max_new_tokens=10).start()
    spec = _engine(model, params, batch_tiles=[1, 2], cache_tiles=[32],
                   max_new_tokens=10, draft_model=draft,
                   draft_variables={"params": dparams}, spec_k=3).start()
    rng = np.random.default_rng(8)
    for n in (2, 5, 8):
        prompt = rng.integers(0, 256, (n,)).astype(np.int32)
        want = base.submit(prompt, max_new_tokens=10).result()
        got = spec.submit(prompt, max_new_tokens=10).result()
        assert got == want, (prompt.tolist(), got, want)
    st = spec.stats()
    assert st["spec_rounds"] > 0
    assert st["spec_proposed"] == 3 * st["spec_rounds"]
    assert 0 <= st["spec_accepted"] <= st["spec_proposed"]
    base.drain()
    spec.drain()


@pytest.mark.slow  # tier-1 budget: heavy pin, slow tier (ISSUE 17 sat. 5)
def test_speculative_greedy_moe_target_self_draft(f32):
    """MoE target drafted by a plain GPT sharing no weights; a SELF-draft
    (draft == target) accepts everything and earns the bonus token."""
    model = _tiny_gpt(seq_len=16, moe_experts=4, moe_top_k=2)
    params = _params(model)
    draft = _tiny_gpt(seq_len=16)
    dparams = _params(draft, key=2)
    base = _engine(model, params, batch_tiles=[1], cache_tiles=[16],
                   prompt_len=4, max_new_tokens=4).start()
    spec = _engine(model, params, batch_tiles=[1], cache_tiles=[16],
                   prompt_len=4, max_new_tokens=4, draft_model=draft,
                   draft_variables={"params": dparams}, spec_k=2).start()
    prompt = np.asarray([10, 20, 30], np.int32)
    assert spec.submit(prompt).result() == base.submit(prompt).result()
    base.drain()
    spec.drain()
    plain = _tiny_gpt(seq_len=32)
    pp = _params(plain)
    selfspec = _engine(plain, pp, batch_tiles=[1], cache_tiles=[32],
                       max_new_tokens=9, draft_model=plain,
                       draft_variables={"params": pp}, spec_k=4).start()
    got = selfspec.submit([1, 2, 3], max_new_tokens=9).result()
    st = selfspec.stats()
    selfspec.drain()
    assert len(got) >= 1
    # a (near-)perfect draft: acceptance ~1. Not exactly 1 — the draft
    # proposes off the T=1 decode executable and the target verifies off
    # the prefill-shaped one, whose reductions may round differently, so
    # a near-tied argmax can flip. The identity pin above is unaffected:
    # rejects correct to the target's own argmax.
    assert st["spec_accepted"] >= 0.7 * st["spec_proposed"]
    assert st["spec_bonus"] >= 1


@pytest.mark.slow  # tier-1 budget: heavy pin, slow tier (ISSUE 17 sat. 5)
def test_speculative_sampled_matches_acceptance_reference(f32):
    """Sampled speculative decode equals the host acceptance-rule
    reference draw for draw (same seed ⇒ same stream), and replays."""
    model = _tiny_gpt(seq_len=32)
    params = _params(model, key=0)
    draft = _tiny_gpt(seq_len=32)
    dparams = _params(draft, key=1)
    sample = {"temperature": 1.1, "top_k": 0, "top_p": 0.9, "seed": 77}

    def run():
        eng = _engine(model, params, batch_tiles=[1], cache_tiles=[32],
                      max_new_tokens=10, draft_model=draft,
                      draft_variables={"params": dparams},
                      spec_k=3).start()
        got = eng.submit([4, 8, 15], max_new_tokens=10,
                         sample=sample).result()
        eng.drain()
        return got

    got = run()
    assert got == run()  # replay
    ref = _host_spec_stream(
        model, {"params": params}, draft, {"params": dparams},
        [4, 8, 15], 10, 3, G.SampleParams(1.1, 0, 0.9, 77), 256, 32,
    )
    assert got == ref


# ------------------------------------------------- tensor-parallel (17a)


def test_tp_divisibility_refusals(f32):
    from distribuuuu_tpu.parallel import mesh as mesh_lib

    mesh = mesh_lib.build_mesh(data=1, model=2, seq=1, pipe=1,
                               devices=jax.devices()[:2])
    from distribuuuu_tpu.models.gpt import GPT

    odd_heads = GPT(vocab_size=320, seq_len=32, dim=33, depth=1,
                    num_heads=3, dtype=jnp.float32)
    with pytest.raises(ValueError, match=r"num_heads=3 \(3 % 2 = 1\)"):
        _engine(odd_heads, _params(odd_heads), mesh=mesh)
    odd_vocab = GPT(vocab_size=321, seq_len=32, dim=32, depth=1,
                    num_heads=2, dtype=jnp.float32)
    with pytest.raises(ValueError, match=r"vocab_size=321 \(321 % 2 = 1\)"):
        _engine(odd_vocab, _params(odd_vocab), mesh=mesh)


def test_tp_decode_matches_single_device(f32):
    """17a pin: a model=2 sharded engine produces the same prefill logits
    (within float tolerance) and the EXACT greedy continuation as the
    single-device engine, from the same training param tree."""
    from distribuuuu_tpu.parallel import mesh as mesh_lib

    model = _tiny_gpt(seq_len=32)
    params = _params(model)
    mesh = mesh_lib.build_mesh(data=1, model=2, seq=1, pipe=1,
                               devices=jax.devices()[:2])
    one = _engine(model, params, batch_tiles=[1, 2], cache_tiles=[32],
                  max_new_tokens=10)
    tp = _engine(model, params, batch_tiles=[1, 2], cache_tiles=[32],
                 max_new_tokens=10, mesh=mesh)
    assert tp._tp == 2
    prompt = np.asarray([3, 1, 4, 1, 5, 9], np.int32)
    padded = np.zeros((1, 8), np.int32)
    padded[0, :6] = prompt
    lg1, _ = one._prefill_exec[8](one._variables, jnp.asarray(padded))
    lg2, _ = tp._prefill_exec[8](tp._variables, jnp.asarray(padded))
    np.testing.assert_allclose(
        np.asarray(lg1)[0, :6], np.asarray(lg2)[0, :6], atol=1e-4,
    )
    one.start()
    tp.start()
    for n in (2, 6):
        p = prompt[:n]
        assert (tp.submit(p, max_new_tokens=10).result()
                == one.submit(p, max_new_tokens=10).result())
    # sampled replay holds on the sharded path too
    sample = {"temperature": 0.9, "seed": 13}
    a = tp.submit(prompt, max_new_tokens=8, sample=sample).result()
    b = tp.submit(prompt, max_new_tokens=8, sample=sample).result()
    assert a == b
    one.drain()
    tp.drain()


def test_tp_speculative_greedy_identity(f32):
    """TP × speculative compose: both model trees sharded on the same
    mesh, stream still identical to the single-device target-only path."""
    from distribuuuu_tpu.parallel import mesh as mesh_lib

    model = _tiny_gpt(seq_len=32)
    params = _params(model, key=0)
    draft = _tiny_gpt(seq_len=32)
    dparams = _params(draft, key=1)
    mesh = mesh_lib.build_mesh(data=1, model=2, seq=1, pipe=1,
                               devices=jax.devices()[:2])
    base = _engine(model, params, batch_tiles=[1], cache_tiles=[32],
                   max_new_tokens=8).start()
    spec_tp = _engine(model, params, batch_tiles=[1], cache_tiles=[32],
                      max_new_tokens=8, mesh=mesh, draft_model=draft,
                      draft_variables={"params": dparams},
                      spec_k=2).start()
    prompt = np.asarray([6, 28, 49, 3], np.int32)
    assert (spec_tp.submit(prompt, max_new_tokens=8).result()
            == base.submit(prompt, max_new_tokens=8).result())
    base.drain()
    spec_tp.drain()


def test_engine_from_cfg_refusals(f32, tmp_path):
    """The from-cfg stanza refusals fire before any engine compiles:
    mesh device arithmetic in-message, non-gpt draft arch by name."""
    from distribuuuu_tpu.lm import service as lm_service

    config.reset_cfg()
    cfg.MODEL.ARCH = "gpt_nano"
    cfg.MODEL.NUM_CLASSES = 320
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.DEVICE.PLATFORM = "cpu"
    cfg.OUT_DIR = str(tmp_path)
    cfg.MESH.DATA = 2
    cfg.MESH.MODEL = 8  # 2 x 8 = 16 > the 8 local (virtual) devices
    with pytest.raises(
        ValueError,
        match=r"MESH.DATA=2 x MESH.MODEL=8 = 16 devices but only 8",
    ):
        lm_service.engine_from_cfg()
    cfg.MESH.DATA = 1
    cfg.MESH.MODEL = 1
    cfg.GENERATE.SPECULATE.ENABLED = True
    cfg.GENERATE.SPECULATE.DRAFT_ARCH = "resnet18"
    with pytest.raises(ValueError, match=r"DRAFT_ARCH='resnet18' is not"):
        lm_service.engine_from_cfg()


@pytest.mark.slow
def test_engine_from_cfg_tp_and_speculate_stanzas(f32, tmp_path):
    """A dp×tp replica + a speculative draft stand up from YAML knobs
    alone (engine_from_cfg), greedy-identical to the single-device
    engine; bad stanzas refuse with the device arithmetic in-message.
    Slow tier: three real gpt_nano engine builds (~80s); the tier-1
    TP/speculative pins above cover the same math on tiny models."""
    from distribuuuu_tpu.lm import service as lm_service

    def base_cfg():
        config.reset_cfg()
        cfg.MODEL.ARCH = "gpt_nano"
        cfg.MODEL.NUM_CLASSES = 320
        cfg.DEVICE.COMPUTE_DTYPE = "float32"
        cfg.DEVICE.PLATFORM = "cpu"
        cfg.LM.SEQ_LEN = 32
        cfg.GENERATE.PROMPT_LEN = 4
        cfg.GENERATE.MAX_NEW_TOKENS = 5
        cfg.GENERATE.BATCH_TILES = [1]
        cfg.GENERATE.CACHE_TILES = [16]
        cfg.RNG_SEED = 0
        cfg.OUT_DIR = str(tmp_path)

    base_cfg()
    one = lm_service.engine_from_cfg().start()
    want = one.submit([1, 2, 3]).result()
    one.drain()

    base_cfg()
    cfg.MESH.DATA = 2
    cfg.MESH.MODEL = 2
    tp = lm_service.engine_from_cfg()
    assert tp._tp == 2
    tp.start()
    assert tp.submit([1, 2, 3]).result() == want
    tp.drain()

    base_cfg()
    cfg.GENERATE.SPECULATE.ENABLED = True
    cfg.GENERATE.SPECULATE.DRAFT_ARCH = "gpt_nano"
    cfg.GENERATE.SPECULATE.K = 2
    cfg.GENERATE.CACHE_TILES = [16]  # 4 + 5 + 2 = 11 <= 16
    spec = lm_service.engine_from_cfg()
    assert spec.spec_k == 2
    spec.start()
    assert spec.submit([1, 2, 3]).result() == want
    spec.drain()

    base_cfg()
    cfg.MESH.DATA = 2
    cfg.MESH.MODEL = 8  # 2 x 8 = 16 > the 8 local (virtual) devices
    with pytest.raises(
        ValueError,
        match=r"MESH.DATA=2 x MESH.MODEL=8 = 16 devices but only 8",
    ):
        lm_service.engine_from_cfg()

    base_cfg()
    cfg.GENERATE.SPECULATE.ENABLED = True
    cfg.GENERATE.SPECULATE.DRAFT_ARCH = "resnet18"
    with pytest.raises(ValueError, match=r"DRAFT_ARCH='resnet18' is not"):
        lm_service.engine_from_cfg()


# ------------------------------------------- telemetry + ctrl satellites


def test_speculative_telemetry_and_run_report(f32, tmp_path):
    """gen.speculate / gen.sample land schema-valid; run_report's lm
    section carries the acceptance-ratio line."""
    import sys

    from distribuuuu_tpu import telemetry
    from distribuuuu_tpu.telemetry import schema

    cfg.OUT_DIR = str(tmp_path)
    telemetry.setup_from_cfg(cfg, rank=0)
    try:
        model = _tiny_gpt(seq_len=32)
        params = _params(model)
        eng = _engine(model, params, batch_tiles=[1], cache_tiles=[32],
                      max_new_tokens=8, draft_model=model,
                      draft_variables={"params": params}, spec_k=2,
                      emit_interval_s=0.0).start()
        eng.submit([1, 2, 3], max_new_tokens=8).result(timeout=120.0)
        eng.submit([4, 5], max_new_tokens=6,
                   sample={"temperature": 0.8, "seed": 3}).result(
                       timeout=120.0)
        eng.drain()
    finally:
        from distribuuuu_tpu.telemetry import spans

        spans.close_telemetry()
    recs = []
    for p in glob.glob(str(tmp_path / "telemetry" / "rank*.jsonl")):
        with open(p) as f:
            recs.extend(json.loads(line) for line in f)
    for r in recs:
        schema.validate_record(r)
    spec = [r for r in recs if r.get("kind") == "gen.speculate"]
    assert spec and all(
        r["proposed"] >= r["accepted"] >= 0 and r["k"] == 2 for r in spec
    )
    samp = [r for r in recs if r.get("kind") == "gen.sample"]
    assert len(samp) == 1 and samp[0]["seed"] == 3
    tools = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    sys.path.insert(0, tools)
    try:
        import run_report

        rep = run_report.build_report(str(tmp_path))
    finally:
        sys.path.remove(tools)
    sp = rep["lm"]["speculate"]
    assert sp["rounds"] == sum(1 for _ in spec)
    assert sp["proposed"] == sum(r["proposed"] for r in spec)
    assert 0.0 <= sp["acceptance_ratio"] <= 1.0
    assert sp["accepted_per_round"] > 1.0  # self-draft: K+1 per round


def test_ctrl_frame_sampling_replays_over_socket(f32):
    """The op="generate" ctrl frame carries temperature/top_k/top_p/seed;
    the same frame replayed against the engine returns the same stream —
    the serving-side replay contract end to end."""
    from distribuuuu_tpu.lm import service as lm_service
    from distribuuuu_tpu.serve import protocol

    model = _tiny_gpt(seq_len=32)
    params = _params(model)
    eng = _engine(model, params, batch_tiles=[1, 2],
                  cache_tiles=[32], max_new_tokens=8).start()
    listener = protocol.open_listener("127.0.0.1", 0)
    port = listener.getsockname()[1]
    stop = threading.Event()
    t = threading.Thread(
        target=protocol.serve_forever,
        args=(eng, listener, stop.is_set), daemon=True,
    )
    t.start()
    try:
        def call(seed):
            frames = list(lm_service.generate_request(
                "127.0.0.1", port, tokens=[9, 8, 7], max_new_tokens=8,
                temperature=1.0, top_p=0.9, seed=seed,
            ))
            assert frames[-1]["stream"] == "done"
            return frames[-1]["tokens"]

        a = call(21)
        assert call(21) == a
        assert call(22) != a
        ref = _host_stream(
            model, {"params": params}, [9, 8, 7], 8,
            G.SampleParams(1.0, 0, 0.9, 21), 256, 32,
        )
        assert a == ref
    finally:
        stop.set()
        t.join(5)
        eng.drain()


# ------------------------------------------------- committed artifacts


def _repo():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_speculative_bench_artifact_committed():
    """BENCH_r11.json: a real A/B with accepted tokens/round > 1, a
    tokens/s win for at least one draft-K, and identical greedy streams."""
    with open(os.path.join(_repo(), "BENCH_r11.json")) as f:
        doc = json.load(f)
    spec = doc["lm_speculative"]
    rows = spec["rows"]
    base = [r for r in rows if r["k"] == 0]
    drafted = [r for r in rows if r["k"] > 0]
    assert len(base) == 1 and {r["k"] for r in drafted} == {2, 4, 8}
    for r in drafted:
        assert r["accepted_per_round"] > 1.0, r
        assert 0.0 < r["acceptance_ratio"] <= 1.0
        assert r["identical_streams"] is True
    assert spec["speedup_best"] > 1.0
    assert any(
        r["tokens_per_s"] > base[0]["tokens_per_s"] for r in drafted
    )
    assert "single core" in spec["note"] or "single-core" in spec["note"]


def test_bench_index_has_lm_spec_series():
    """The r11 series index under lm_spec_* and cannot collide with the
    img/s throughput gate (the PR 8 clobbering lesson)."""
    import sys

    tools = os.path.join(_repo(), "tools")
    sys.path.insert(0, tools)
    try:
        import bench_history

        index = bench_history.build_index(_repo())
    finally:
        sys.path.remove(tools)
    series = index["series"]
    for k in (2, 4, 8):
        assert f"lm_spec_tokens_per_s_k{k}" in series
        assert f"lm_spec_acceptance_k{k}" in series
    assert "lm_spec_tokens_per_s_k0" in series
    assert "lm_spec_speedup_best" in series
    for name in series:
        if name.startswith("lm_spec"):
            assert "images_per_sec" not in name
            assert "img_per_sec" not in name
    with open(os.path.join(_repo(), "BENCH_INDEX.json")) as f:
        committed = json.load(f)
    assert committed["series"] == series, (
        "BENCH_INDEX.json is stale — rerun tools/bench_history.py"
    )


def test_lm_decode_campaign_artifact_committed():
    """SERVE_CAMPAIGN_r02.json carries the lm_decode campaign: streaming
    generate through the fleet router, backpressure raised in the crowd
    phase and ONLY there, control/drain clean."""
    with open(os.path.join(_repo(), "SERVE_CAMPAIGN_r02.json")) as f:
        doc = json.load(f)
    assert doc["ok"] is True
    lm = next(
        c for c in doc["campaigns"] if c["campaign"] == "lm_decode"
    )
    assert lm["ok"] and lm["alerts_exact"] and lm["control_clean"]
    assert lm["deterministic"]
    phases = {p["name"]: p for p in lm["phases"]}
    assert phases["crowd"]["raised"] == ["backpressure"]
    assert phases["crowd"]["counts"]["busy"] > 0  # the burst DID bounce
    assert phases["control"]["raised"] == []
    assert phases["drain"]["raised"] == []
    assert phases["crowd"]["counts"]["failed"] == 0  # admitted ⇒ served
