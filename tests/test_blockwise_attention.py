"""blockwise_attention: exact flash-style single-device attention in
O(L·chunk) memory — numerics vs the dense oracle, gradients, causal and
ragged-chunk cases, and the ViT integration."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distribuuuu_tpu.ops.ring_attention import (
    blockwise_attention,
    reference_attention,
)


def _qkv(rng, b=2, h=3, L=260, d=16):
    return (
        jnp.asarray(rng.standard_normal((b, h, L, d)), jnp.float32)
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("L,chunk", [(256, 64), (260, 64), (100, 512)])
def test_matches_dense_reference(causal, L, chunk):
    rng = np.random.default_rng(0)
    q, k, v = _qkv(rng, L=L)
    ref = reference_attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, chunk=chunk, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_gradients_match_dense():
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, L=130)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v) ** 2)

    def loss_blk(q, k, v):
        return jnp.sum(blockwise_attention(q, k, v, chunk=32) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_blk = jax.grad(loss_blk, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_blk):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4
        )


def test_remat_off_matches_remat_on():
    rng = np.random.default_rng(2)
    q, k, v = _qkv(rng, L=96)
    a = blockwise_attention(q, k, v, chunk=32, remat=True)
    b = blockwise_attention(q, k, v, chunk=32, remat=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


@pytest.mark.slow
def test_vit_blockwise_matches_xla_impl():
    """Same weights, attn_impl xla vs blockwise → same logits (and the
    DEVICE.ATTN_IMPL wiring reaches the model)."""
    import distribuuuu_tpu.config as config
    from distribuuuu_tpu import models, trainer
    from distribuuuu_tpu.config import cfg

    x = jnp.asarray(
        np.random.default_rng(3).standard_normal((2, 32, 32, 3)), jnp.float32
    )
    dense = models.build_model(
        "vit_tiny", num_classes=10, dtype=jnp.float32, dropout=0.0
    )
    blockwise = models.build_model(
        "vit_tiny", num_classes=10, dtype=jnp.float32, dropout=0.0,
        attn_impl="blockwise",
    )
    variables = dense.init(jax.random.key(0), x, train=False)
    a = dense.apply(variables, x, train=False)
    b = blockwise.apply(variables, x, train=False)
    np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4
    )

    config.reset_cfg()
    cfg.MODEL.ARCH = "vit_tiny"
    cfg.DEVICE.ATTN_IMPL = "blockwise"
    assert trainer.build_model_from_cfg().attn_impl == "blockwise"

    # misconfigurations surface at build time, not as silent dense fallback
    cfg.DEVICE.ATTN_IMPL = "blockwsie"
    with pytest.raises(ValueError, match="ATTN_IMPL"):
        trainer.build_model_from_cfg()
    cfg.DEVICE.ATTN_IMPL = "ring"  # needs MESH.SEQ > 1
    with pytest.raises(ValueError, match="MESH.SEQ"):
        trainer.build_model_from_cfg()
