"""C++ decode kernel vs the PIL reference path.

The native backend must (a) produce the SAME augmentation geometry (it shares
the numpy RNG stream with the PIL path) and (b) match pixel values up to
resampler quantization (PIL uses fixed-point uint8 arithmetic, the kernel
float with a uint8 intermediate — bounded by a few counts per channel).
"""

import numpy as np
import pytest
from PIL import Image

from distribuuuu_tpu import native
from distribuuuu_tpu.data.imagefolder import ImageFolderDataset
from distribuuuu_tpu.data import transforms as T

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native kernel unavailable: {native.build_error()}"
)

# normalized-space tolerance: 3/255 per channel / min(std) ≈ 0.053
ATOL = 0.06


def _make_tree(root, fmt="JPEG", sizes=((96, 64), (64, 96), (150, 150))):
    rng = np.random.default_rng(0)
    for cls in ("cat", "dog"):
        d = root / "train" / cls
        d.mkdir(parents=True)
        for i, (w, h) in enumerate(sizes):
            arr = rng.integers(0, 256, size=(h, w, 3), dtype=np.uint8)
            ext = "jpg" if fmt == "JPEG" else "png"
            Image.fromarray(arr).save(d / f"{i}.{ext}", fmt, quality=95)
    # val split mirrors train
    import shutil

    shutil.copytree(root / "train", root / "val")


def _dataset(root, split, train, backend):
    return ImageFolderDataset(
        str(root), split,
        im_size=32 if train else 48,
        train=train,
        base_seed=7,
        crop_size=None if train else 32,
        backend=backend,
    )


@pytest.mark.parametrize("fmt", ["JPEG", "PNG"])
@pytest.mark.parametrize("train", [True, False])
def test_native_matches_pil(tmp_path, fmt, train):
    _make_tree(tmp_path, fmt)
    split = "train" if train else "val"
    ds_nat = _dataset(tmp_path, split, train, "native")
    ds_pil = _dataset(tmp_path, split, train, "pil")
    ds_nat.set_epoch_seed(3)
    ds_pil.set_epoch_seed(3)
    idxs = np.arange(len(ds_nat))
    img_nat, lab_nat = ds_nat.load_batch(idxs, n_threads=3)
    img_pil, lab_pil = ds_pil.load_batch(idxs, n_threads=3)
    np.testing.assert_array_equal(lab_nat, lab_pil)
    assert img_nat.shape == img_pil.shape
    diff = np.abs(img_nat - img_pil)
    assert diff.max() < ATOL, f"max diff {diff.max():.4f}"
    assert diff.mean() < 0.01


def test_grayscale_jpeg(tmp_path):
    d = tmp_path / "train" / "x"
    d.mkdir(parents=True)
    arr = np.random.default_rng(1).integers(0, 256, size=(80, 60), dtype=np.uint8)
    Image.fromarray(arr, mode="L").save(d / "g.jpg", "JPEG", quality=95)
    ds_nat = _dataset(tmp_path, "train", True, "native")
    ds_pil = _dataset(tmp_path, "train", True, "pil")
    img_nat, _ = ds_nat.load_batch([0])
    img_pil, _ = ds_pil.load_batch([0])
    assert np.abs(img_nat - img_pil).max() < ATOL


def test_exotic_format_falls_back(tmp_path):
    d = tmp_path / "train" / "x"
    d.mkdir(parents=True)
    arr = np.random.default_rng(2).integers(0, 256, size=(40, 40, 3), dtype=np.uint8)
    Image.fromarray(arr).save(d / "img.bmp", "BMP")
    ds = _dataset(tmp_path, "train", True, "native")
    imgs, labs = ds.load_batch([0])
    ref = ds[0][0]
    np.testing.assert_allclose(imgs[0], ref, atol=1e-6)


def test_file_dims(tmp_path):
    p = tmp_path / "a.jpg"
    Image.fromarray(np.zeros((30, 50, 3), np.uint8)).save(p, "JPEG")
    assert native.file_dims(str(p)) == (50, 30)


def test_geometry_stream_parity():
    """train_geom must consume the RNG exactly like train_transform."""
    rng_a = np.random.default_rng(42)
    rng_b = np.random.default_rng(42)
    g = T.train_geom(100, 80, 32, rng_a)
    img = Image.fromarray(np.zeros((80, 100, 3), np.uint8))
    T.train_transform(img, 32, rng_b)
    # After identical draw sequences the streams must be in the same state.
    assert rng_a.random() == rng_b.random()
    assert len(g) == 7
