"""Data layer tests: sampler semantics (vs torch oracle), ImageFolder,
transforms, loader batching."""

import numpy as np
import pytest
import torch
from PIL import Image

from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.data.dummy import DummyDataset
from distribuuuu_tpu.data.loader import Loader
from distribuuuu_tpu.data.sampler import DistributedSampler
from distribuuuu_tpu.data.transforms import (
    center_crop,
    random_resized_crop,
    resize_shorter,
    to_normalized_array,
)


# ----------------------------------------------------------------- sampler
def test_sampler_partitions_exactly():
    n, world = 100, 4
    seen = []
    for rank in range(world):
        s = DistributedSampler(n, world, rank, shuffle=False)
        idxs = s.indices()
        assert len(idxs) == 25
        seen.extend(idxs.tolist())
    assert sorted(seen) == list(range(100))


def test_sampler_pads_like_torch():
    """Uneven dataset: total padded to world multiple by wrapping, matching
    torch.utils.data.distributed.DistributedSampler (ref: utils.py:141-143)."""
    n, world = 10, 4
    ours_all, torch_all = [], []
    for rank in range(world):
        ours = DistributedSampler(n, world, rank, shuffle=False).indices()
        ts = torch.utils.data.distributed.DistributedSampler(
            list(range(n)), num_replicas=world, rank=rank, shuffle=False
        )
        tidx = list(iter(ts))
        assert ours.tolist() == tidx, f"rank {rank}: {ours} vs {tidx}"
        ours_all.extend(ours.tolist())
        torch_all.extend(tidx)
    assert len(ours_all) == 12  # ceil(10/4)*4


def test_sampler_shuffle_reshuffles_with_epoch():
    s = DistributedSampler(50, 2, 0, shuffle=True, seed=7)
    s.set_epoch(0)
    e0 = s.indices().tolist()
    s.set_epoch(1)
    e1 = s.indices().tolist()
    assert e0 != e1
    s.set_epoch(0)
    assert s.indices().tolist() == e0  # deterministic per epoch


# -------------------------------------------------------------- transforms
def _make_img(w, h):
    rgb = np.zeros((h, w, 3), np.uint8)
    rgb[:, :, 0] = np.linspace(0, 255, w, dtype=np.uint8)[None, :]
    return Image.fromarray(rgb)


def test_resize_shorter_keeps_aspect():
    img = resize_shorter(_make_img(400, 200), 100)
    assert img.size == (200, 100)
    img = resize_shorter(_make_img(200, 400), 100)
    assert img.size == (100, 200)


def test_center_crop():
    img = center_crop(_make_img(300, 200), 100)
    assert img.size == (100, 100)


def test_random_resized_crop_output_size():
    rng = np.random.default_rng(0)
    for _ in range(5):
        out = random_resized_crop(_make_img(250, 180), 64, rng)
        assert out.size == (64, 64)


def test_to_normalized_array_range():
    arr = to_normalized_array(_make_img(10, 10))
    assert arr.shape == (10, 10, 3)
    assert arr.dtype == np.float32
    # channel 0 spans the gradient; normalized values in plausible range
    assert arr.min() > -3.0 and arr.max() < 3.0


# -------------------------------------------------------------- imagefolder
@pytest.fixture
def fake_imagefolder(tmp_path):
    rng = np.random.default_rng(0)
    for split in ("train", "val"):
        for cls in ("class_a", "class_b", "class_c"):
            d = tmp_path / split / cls
            d.mkdir(parents=True)
            for i in range(4):
                arr = rng.integers(0, 255, (40, 50, 3), np.uint8)
                Image.fromarray(arr.astype(np.uint8)).save(d / f"img_{i}.jpg")
    return tmp_path


def test_imagefolder_scan_and_getitem(fake_imagefolder):
    from distribuuuu_tpu.data.imagefolder import ImageFolderDataset

    ds = ImageFolderDataset(str(fake_imagefolder), "train", im_size=32, train=True)
    assert len(ds) == 12
    assert ds.classes == ["class_a", "class_b", "class_c"]
    img, label = ds[0]
    assert img.shape == (32, 32, 3) and img.dtype == np.float32
    assert label == 0
    img, label = ds[11]
    assert label == 2
    # val path: resize 36 + center crop 32
    dv = ImageFolderDataset(str(fake_imagefolder), "val", im_size=36, train=False)
    img, _ = dv[0]
    assert img.shape[2] == 3  # crop default is 224 > image — exercised below


def test_imagefolder_missing_root_message():
    from distribuuuu_tpu.data.imagefolder import ImageFolderDataset

    with pytest.raises(FileNotFoundError, match="DUMMY_INPUT"):
        ImageFolderDataset("/nonexistent", "train", im_size=32, train=True)


def test_imagefolder_augmentation_varies_with_epoch(fake_imagefolder):
    from distribuuuu_tpu.data.imagefolder import ImageFolderDataset

    ds = ImageFolderDataset(str(fake_imagefolder), "train", im_size=32, train=True)
    ds.set_epoch_seed(0)
    a0, _ = ds[3]
    ds.set_epoch_seed(1)
    a1, _ = ds[3]
    ds.set_epoch_seed(0)
    a0b, _ = ds[3]
    assert not np.allclose(a0, a1)
    np.testing.assert_array_equal(a0, a0b)


# ------------------------------------------------------------------ loader
def test_loader_drop_last_and_padding():
    ds = DummyDataset(length=10, size=8)
    train = Loader(ds, batch_size=4, shuffle=False, drop_last=True, workers=1)
    batches = list(train)
    assert len(batches) == len(train) == 2  # 10 -> 2 full batches, tail dropped
    assert all(b["image"].shape == (4, 8, 8, 3) for b in batches)
    assert all(b["mask"].sum() == 4 for b in batches)

    val = Loader(ds, batch_size=4, shuffle=False, drop_last=False, workers=1)
    batches = list(val)
    assert len(batches) == len(val) == 3
    assert batches[-1]["image"].shape == (4, 8, 8, 3)  # padded to full shape
    assert batches[-1]["mask"].tolist() == [1.0, 1.0, 0.0, 0.0]


def test_loader_epoch_reshuffle_changes_order():
    ds = DummyDataset(length=16, size=4)
    loader = Loader(ds, batch_size=4, shuffle=True, drop_last=True, workers=1)
    loader.set_epoch(0)
    l0 = [b["image"].sum() for b in loader]
    loader.set_epoch(1)
    l1 = [b["image"].sum() for b in loader]
    assert l0 != l1
