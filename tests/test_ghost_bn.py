"""Ghost (grouped) BatchNorm vs global-batch (SyncBN) statistics.

The reference's published baselines all train with ``SYNCBN: False`` — BN
statistics over one GPU's 32–64 samples (ref: /root/reference/distribuuuu/
trainer.py:131 opt-in convert; config/resnet50.yaml SYNCBN False). Ghost BN
(``models/layers._BNCore`` with ``group_size=g``) reproduces that regime on
any chip count; ``group_size=0`` is the global-batch (SyncBatchNorm) path.

Oracles here:
  - torch.nn.BatchNorm2d run per group == ghost BN run on the full batch
    (normalization AND running-stat updates, incl. torch's unbiased
    running-var convention),
  - group stats ≠ global stats on a heterogeneous sharded batch,
  - the trainer honors MODEL.SYNCBN / MODEL.BN_GROUP,
  - indivisible group sizes raise (no silent fallback).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.models.layers import BatchNorm


def _bn_apply(group_size, x, train=True):
    bn = BatchNorm(dtype=jnp.float32, group_size=group_size)
    vs = bn.init(jax.random.key(0), x, train=False)
    y, mut = bn.apply(vs, x, train=train, mutable=["batch_stats"])
    return np.asarray(y), jax.tree.map(np.asarray, mut["batch_stats"])


def test_ghost_bn_matches_torch_per_group():
    """Each 32-sample group is normalized exactly as torch BN normalizes
    that group alone (the per-GPU semantics of the reference recipes)."""
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(0)
    # heterogeneous groups: shift+scale group 1 so stats differ strongly
    x = rng.standard_normal((64, 4, 4, 8)).astype(np.float32)
    x[32:] = x[32:] * 3.0 + 5.0

    y, stats = _bn_apply(32, jnp.asarray(x))

    tb = torch.nn.BatchNorm2d(8, eps=1e-5, momentum=0.1)
    tb.train()
    xt = torch.from_numpy(x.transpose(0, 3, 1, 2))
    with torch.no_grad():
        y_groups = [tb(xt[:32]).numpy(), ]
    # fresh torch module for the second group: ghost groups are independent
    tb2 = torch.nn.BatchNorm2d(8, eps=1e-5, momentum=0.1)
    tb2.train()
    with torch.no_grad():
        y_groups.append(tb2(xt[32:]).numpy())
    yt = np.concatenate(y_groups).transpose(0, 2, 3, 1)
    np.testing.assert_allclose(y, yt, atol=2e-5)

    # running stats: ghost BN averages the per-group (torch-unbiased)
    # estimates in ONE momentum update
    leaves = jax.tree.leaves(stats)  # insertion order: mean, var
    mean_upd = 0.5 * (tb.running_mean.numpy() + tb2.running_mean.numpy())
    var_upd = 0.5 * (tb.running_var.numpy() + tb2.running_var.numpy())
    np.testing.assert_allclose(leaves[0], mean_upd, atol=1e-5)
    np.testing.assert_allclose(leaves[1], var_upd, rtol=1e-5)


def test_global_bn_matches_torch_full_batch():
    """group_size=0 == torch BN over the whole batch (SyncBN semantics)."""
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(1)
    x = rng.standard_normal((16, 3, 3, 4)).astype(np.float32)
    y, stats = _bn_apply(0, jnp.asarray(x))
    tb = torch.nn.BatchNorm2d(4, eps=1e-5, momentum=0.1)
    tb.train()
    with torch.no_grad():
        yt = tb(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(y, yt.transpose(0, 2, 3, 1), atol=2e-5)
    leaves = jax.tree.leaves(stats)
    np.testing.assert_allclose(leaves[0], tb.running_mean.numpy(), atol=1e-5)
    np.testing.assert_allclose(leaves[1], tb.running_var.numpy(), rtol=1e-5)


def _bn_apply_warm(group_size, x, warm_mean, train=True):
    """Apply BN with the running mean pre-set to ``warm_mean`` — the
    steady-state the one-pass *shifted* variance (shift = running mean,
    r4) is designed for. The shift only has to land within ~√(var/ε_fp32)
    of the batch mean for the E[d²]−E[d]² identity to be exact to fp32."""
    bn = BatchNorm(dtype=jnp.float32, group_size=group_size)
    vs = bn.init(jax.random.key(0), x, train=False)
    vs = jax.tree.map(lambda v: v, vs)  # unfreeze-safe shallow copy
    vs["batch_stats"]["BatchNorm_0"]["mean"] = jnp.full(
        (x.shape[-1],), warm_mean, jnp.float32
    )
    y, mut = bn.apply(vs, x, train=train, mutable=["batch_stats"])
    return np.asarray(y), jax.tree.map(np.asarray, mut["batch_stats"])


def test_bn_large_mean_numerics_match_torch():
    """Large mean relative to spread (mean ~1e3, spread ~1e-2): with a
    running mean tracking the input scale — the steady state after any
    training — the one-pass shifted variance (r4, var = E[d²]−E[d]² with
    d = x − running_mean) is exact where E[x²]−E[x]² cancels
    catastrophically (var ~1e-4 drowns in the ~0.1 absolute rounding of
    1e6-scale squares; ADVICE r2). The shift need not be exact: anything
    within ~√(var/ε_fp32) ≈ 4 of the true mean suffices; 1e3 vs the
    batch's 1e3+O(1e-2) is far inside that."""
    torch = pytest.importorskip("torch")
    rng = np.random.default_rng(7)
    x = (1e3 + 1e-2 * rng.standard_normal((32, 2, 2, 4))).astype(np.float32)
    tb = torch.nn.BatchNorm2d(4, eps=1e-5, momentum=0.1)
    tb.train()
    with torch.no_grad():
        yt = tb(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    # global (SyncBN) path: the running-var estimate is the direct probe
    # of the variance formulation (cancellation gives ≤0 or garbage); the
    # normalized output tolerates fp32 mean-accumulation rounding, which
    # differs between jnp and torch at this scale. torch's own running
    # mean starts at 0, so compare running var only (the momentum-mixed
    # running mean trivially agrees: both are 0.1·batch_mean).
    y, stats = _bn_apply_warm(0, jnp.asarray(x), 1e3)
    np.testing.assert_allclose(
        y, yt.transpose(0, 2, 3, 1), atol=0.1
    )
    np.testing.assert_allclose(
        jax.tree.leaves(stats)[1], tb.running_var.numpy(), rtol=0.02
    )
    # ghost path: each group must still normalize to ~N(0,1) — an
    # unshifted cancelling formulation gives negative variance (⇒ NaN)
    yg, _ = _bn_apply_warm(16, jnp.asarray(x), 1e3)
    assert np.isfinite(yg).all()
    assert abs(float(yg.mean())) < 1e-2
    assert abs(float(yg.std()) - 1.0) < 0.1


def test_bn_large_mean_cold_start_stays_finite():
    """The documented regime bound of the shifted one-pass variance: a
    cold-start batch (running mean still 0) with |mean| ≫ spread rounds
    like the uncentered form. The var ≥ 0 clamp guarantees the output is
    finite (rsqrt never sees a negative), training can proceed, and the
    running mean converges toward the scale — after which the previous
    test's exactness applies."""
    rng = np.random.default_rng(8)
    x = (1e3 + 1e-2 * rng.standard_normal((32, 2, 2, 4))).astype(np.float32)
    for gs in (0, 16):
        y, stats = _bn_apply(gs, jnp.asarray(x))
        assert np.isfinite(y).all()
        # running mean moved toward the batch mean (momentum 0.9 ⇒ 0.1·1e3)
        np.testing.assert_allclose(
            jax.tree.leaves(stats)[0], 100.0, rtol=1e-3
        )


def test_group_stats_differ_from_global_on_sharded_batch():
    """On a batch whose shards have different distributions, ghost and
    global BN produce measurably different outputs — the regime matters."""
    from distribuuuu_tpu.parallel import mesh as mesh_lib

    rng = np.random.default_rng(2)
    x = rng.standard_normal((64, 2, 2, 4)).astype(np.float32)
    x[32:] = x[32:] * 4.0 + 10.0  # second half: very different stats
    mesh = mesh_lib.build_mesh()  # 8 virtual CPU devices on the data axis
    xs = jax.device_put(
        jnp.asarray(x),
        jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec("data", None, None, None)
        ),
    )
    y_ghost, _ = _bn_apply(32, xs)
    y_global, _ = _bn_apply(0, xs)
    assert np.abs(y_ghost - y_global).max() > 0.1


def test_ghost_equals_global_when_group_is_whole_batch():
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((8, 2, 2, 4)).astype(np.float32))
    y_g, st_g = _bn_apply(8, x)
    y_0, st_0 = _bn_apply(0, x)
    np.testing.assert_allclose(y_g, y_0, atol=1e-6)
    for a, b in zip(jax.tree.leaves(st_g), jax.tree.leaves(st_0)):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_indivisible_group_raises():
    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((10, 2, 2, 4)).astype(np.float32))
    with pytest.raises(ValueError, match="ghost BN"):
        _bn_apply(4, x)


def test_eval_uses_running_stats_regardless_of_group():
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((8, 2, 2, 4)).astype(np.float32))
    y_g, _ = _bn_apply(4, x, train=False)
    y_0, _ = _bn_apply(0, x, train=False)
    np.testing.assert_allclose(y_g, y_0, atol=1e-6)


def test_trainer_honors_syncbn_flag():
    from distribuuuu_tpu import trainer

    cfg.TRAIN.BATCH_SIZE = 32
    assert trainer.bn_group_from_cfg() == 32  # SYNCBN False default
    cfg.MODEL.BN_GROUP = 16
    assert trainer.bn_group_from_cfg() == 16
    cfg.MODEL.SYNCBN = True
    assert trainer.bn_group_from_cfg() == 0  # global stats

    cfg.MODEL.SYNCBN = False
    cfg.MODEL.BN_GROUP = 0
    model = trainer.build_model_from_cfg()
    assert model.bn_group == 32
    cfg.MODEL.SYNCBN = True
    model = trainer.build_model_from_cfg()
    assert model.bn_group == 0


@pytest.mark.slow
def test_resnet18_trains_with_ghost_bn():
    """End-to-end: one jitted train step with ghost groups ≠ one with
    global stats (same init, same batch) — the flag reaches the graph."""
    from distribuuuu_tpu import models
    from distribuuuu_tpu.utils.metrics import cross_entropy

    rng = np.random.default_rng(6)
    x = rng.standard_normal((16, 32, 32, 3)).astype(np.float32)
    x[8:] = x[8:] * 2.0 + 1.0
    y = rng.integers(0, 10, size=(16,)).astype(np.int32)

    outs = {}
    for name, g in (("ghost", 8), ("global", 0)):
        model = models.build_model(
            "resnet18", num_classes=10, dtype=jnp.float32, bn_group=g
        )
        vs = model.init(jax.random.key(0), jnp.ones((2, 32, 32, 3)), train=False)

        @jax.jit
        def loss_fn(params, stats, images, labels):
            logits, mut = model.apply(
                {"params": params, "batch_stats": stats},
                images, train=True, mutable=["batch_stats"],
            )
            return cross_entropy(logits, labels)

        outs[name] = float(
            loss_fn(vs["params"], vs["batch_stats"], jnp.asarray(x), jnp.asarray(y))
        )
    assert outs["ghost"] != pytest.approx(outs["global"], abs=1e-7)
