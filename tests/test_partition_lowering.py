"""The one lowering (parallel/partition/lowering.py): trajectory
equivalence against the hand-assembled legacy path for every shipped
topology class, and the ISSUE 9 acceptance compositions — ZeRO-3 under
PP and a dp×tp×ep 3-axis mesh with ZeRO-1 — training from a YAML mesh
stanza alone on the 8-device CPU mesh."""

import tempfile

import numpy as np
import jax
import pytest

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer
from distribuuuu_tpu.parallel import mesh as mesh_lib, sharding as sharding_lib
from distribuuuu_tpu.parallel.partition import lowering, topology
from distribuuuu_tpu.utils.optim import construct_optimizer

N_STEPS = 3


def stream_batch(step: int, n: int = 16, im: int = 32):
    rng = np.random.default_rng(11_000 + step)
    images = rng.standard_normal((n, im, im, 3)).astype(np.float32)
    labels = (
        (images.mean(axis=(1, 2, 3)) * 40.0).astype(np.int64) % 10
    ).astype(np.int32)
    images += labels[:, None, None, None] * 0.1
    return {
        "image": images, "label": labels, "mask": np.ones((n,), np.float32)
    }


def _merge_stanza(yaml_text: str):
    with tempfile.NamedTemporaryFile("w", suffix=".yaml") as f:
        f.write(yaml_text)
        f.flush()
        cfg.merge_from_file(f.name)


def _run_lowered(n_steps=N_STEPS, batch=16, im=32, seed=0):
    """The full partition path: registry → lowering → steps, as
    train_model wires it."""
    topo = trainer.check_trainer_mesh()
    mesh = mesh_lib.mesh_from_cfg(cfg)
    model = trainer.build_model_from_cfg(topo)
    low = lowering.lower(
        model, construct_optimizer(), 5, mesh=mesh, topology=topo, im_size=im
    )
    state = trainer.create_train_state(
        model, jax.random.key(seed), mesh, im, layout=low.layout
    )
    losses = []
    for it in range(n_steps):
        state, m = low.train_step(
            state, low.put_batch(stream_batch(it, batch, im))
        )
        losses.append(float(m["loss"]))
    return low, state, losses


def _run_legacy(n_steps=N_STEPS, batch=16, im=32, seed=0):
    """The pre-r11 hand assembly: _state_layout + make_train_step with the
    layout passed only when ZeRO is on."""
    mesh = mesh_lib.mesh_from_cfg(cfg)
    model = trainer.build_model_from_cfg()
    layout = trainer._state_layout(model, mesh, im) if cfg.MESH.ZERO else None
    state = trainer.create_train_state(
        model, jax.random.key(seed), mesh, im, layout=layout
    )
    step = trainer.make_train_step(
        model, construct_optimizer(), topk=5, layout=layout
    )
    losses = []
    for it in range(n_steps):
        state, m = step(
            state, sharding_lib.shard_batch(mesh, stream_batch(it, batch, im))
        )
        losses.append(float(m["loss"]))
    return state, losses


def _assert_lockstep(traj, base):
    """The repo's lockstep tolerance (tests/test_zero.py): step-0 loss is
    pre-update (identical init) — tight; later steps bounded by XLA
    reduction-order drift."""
    assert np.isfinite(traj).all(), traj
    np.testing.assert_allclose(traj[0], base[0], rtol=0, atol=1e-5)
    np.testing.assert_allclose(traj[1], base[1], rtol=0, atol=2e-2)
    assert abs(traj[2] - base[2]) < 0.5, (traj, base)


# ------------------------------------------------- acceptance compositions


def test_zero3_under_pp_trains_from_stanza_alone():
    """ZeRO-3 × PP — flatly refused before r11 (trainer.py:92-96) — trains
    from a YAML mesh stanza alone: FSDP params rest data-sharded, gather
    at the stage shard_map boundary, backward reduce-scatters."""
    config.reset_cfg()
    _merge_stanza(
        "MODEL: {ARCH: vit_tiny, NUM_CLASSES: 10}\n"
        "TRAIN: {IM_SIZE: 32}\n"
        "DEVICE: {COMPUTE_DTYPE: float32}\n"
        "MESH: {DATA: 2, PIPE: 4, MICROBATCH: 4, ZERO: 3}\n"
    )
    low, state, losses = _run_lowered(n_steps=2)
    assert np.isfinite(losses).all(), losses
    assert losses[1] < losses[0]  # the update actually lands on the layout

    # params genuinely deduplicated over data AT REST (shard accounting,
    # not specs): the composition is a layout, not a fallback
    deduped = 0
    for leaf in jax.tree.leaves(state.params):
        spec = getattr(leaf.sharding, "spec", ())
        names = {
            n for e in spec if e for n in ((e,) if isinstance(e, str) else e)
        }
        if "data" in names and leaf.addressable_shards[0].data.size < leaf.size:
            deduped += 1
    assert deduped >= 10, deduped


@pytest.mark.slow  # 41s: 3-axis mesh compile + train; tier-1 budget
def test_three_axis_ep_with_zero1_trains_from_stanza_alone():
    """dp2×tp2×ep2 + ZeRO-1 — pathless before r11 (no expert axis
    existed) — trains from a YAML stanza alone: experts on the dedicated
    axis, dense kernels on the TP axis, optimizer state ZeRO'd over
    data."""
    config.reset_cfg()
    _merge_stanza(
        "MODEL: {ARCH: vit_tiny_moe, NUM_CLASSES: 10}\n"
        "TRAIN: {IM_SIZE: 32}\n"
        "DEVICE: {COMPUTE_DTYPE: float32}\n"
        "MESH: {DATA: 2, MODEL: 2, EXPERT: 2, ZERO: 1}\n"
    )
    low, state, losses = _run_lowered(n_steps=2)
    assert np.isfinite(losses).all(), losses
    assert low.topology.moe_axis() == "expert"

    def axes_of(leaf):
        spec = getattr(leaf.sharding, "spec", ())
        return {
            n for e in spec if e for n in ((e,) if isinstance(e, str) else e)
        }

    p_axes = [axes_of(leaf) for leaf in jax.tree.leaves(state.params)]
    assert any("expert" in a for a in p_axes)  # expert tensors on ep
    assert any("model" in a for a in p_axes)   # dense kernels on tp
    zeroed = sum(
        1
        for leaf in jax.tree.leaves(state.opt_state)
        if hasattr(leaf, "sharding") and "data" in axes_of(leaf)
        and leaf.addressable_shards[0].data.size < leaf.size
    )
    assert zeroed >= 10, zeroed


# ------------------------------------------- equivalence vs the legacy path


@pytest.mark.slow  # 38s: legacy-vs-lowering A/B train; tier-1 budget (ISSUE 18)
def test_lowering_reproduces_legacy_dp_zero1():
    """dp8 + ZeRO-1 (resnet18): the declarative path and the hand
    assembly build the same program — trajectories agree to float-drift
    tolerance from the same seeds/stream."""
    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.BN_GROUP = 8
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.MESH.ZERO = 1
    _, _, traj = _run_lowered()
    base, base_traj = _run_legacy()
    _assert_lockstep(traj, base_traj)


@pytest.mark.slow
@pytest.mark.parametrize(
    "stanza",
    [
        {"MODEL.ARCH": "resnet18"},                                   # dp
        {"MODEL.ARCH": "resnet18", "MESH.MODEL": 2},                  # dp×tp
        {"MODEL.ARCH": "resnet18", "MESH.ZERO": 3},                   # fsdp
        {"MODEL.ARCH": "vit_tiny", "MESH.PIPE": 4,
         "MESH.MICROBATCH": 4},                                       # pp
        {"MODEL.ARCH": "vit_tiny_moe", "MESH.MODEL": 2},              # ep
    ],
    ids=["dp", "dp_tp", "zero3", "pp", "moe"],
)
def test_lowering_reproduces_legacy_topologies(stanza):
    """Every shipped topology class: new lowering vs legacy assembly at
    the lockstep tolerance."""
    config.reset_cfg()
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.BN_GROUP = 8
    cfg.TRAIN.IM_SIZE = 32
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    flat = [x for kv in stanza.items() for x in kv]
    cfg.merge_from_list(list(map(str, flat)))
    _, _, traj = _run_lowered()
    _, base_traj = _run_legacy()
    _assert_lockstep(traj, base_traj)


@pytest.mark.slow
def test_zero3_pp_trajectory_matches_stage0():
    """ZeRO-3 under PP is a LAYOUT: the trajectory matches the stage-0 PP
    run at the lockstep tolerance (same contract test_zero.py pins for
    the other stages)."""

    def run(stage):
        config.reset_cfg()
        cfg.MODEL.ARCH = "vit_tiny"
        cfg.MODEL.NUM_CLASSES = 10
        cfg.TRAIN.IM_SIZE = 32
        cfg.DEVICE.COMPUTE_DTYPE = "float32"
        cfg.MESH.PIPE = 4
        cfg.MESH.MICROBATCH = 4
        cfg.MESH.DATA = -1
        cfg.MESH.ZERO = stage
        _, _, losses = _run_lowered()
        return losses

    traj = run(3)
    base = run(0)
    _assert_lockstep(traj, base)


def test_lowered_fold_and_accum_paths_build():
    """The folded/accumulated variants build through the same lowering
    (fold>1 → scan_step; accum routes put_batch to the micro split)."""
    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.BN_GROUP = 4
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    topo = trainer.check_trainer_mesh()
    mesh = mesh_lib.mesh_from_cfg(cfg)
    model = trainer.build_model_from_cfg(topo)
    low = lowering.lower(
        model, construct_optimizer(), 5, mesh=mesh, topology=topo,
        im_size=32, fold=2, accum=2,
    )
    assert low.scan_step is not None
    state = trainer.create_train_state(
        model, jax.random.key(0), mesh, 32, layout=low.layout
    )
    host = stream_batch(0)
    stacked = {k: np.stack([v, v]) for k, v in host.items()}
    state, metrics = low.scan_step(state, low.put_stacked(stacked))
    losses = np.asarray(metrics["loss"])
    assert losses.shape == (2,) and np.isfinite(losses).all()
