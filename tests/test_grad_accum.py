"""TRAIN.GRAD_ACCUM_STEPS: in-graph gradient accumulation must reproduce the
full-batch optimizer step exactly on stat-free models (mean-CE micro-grads
average to the full-batch grad), and run e2e through the trainer."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg

pytestmark = pytest.mark.slow  # multi-minute on the 1-core CPU mesh


class _TinyMLP(nn.Module):
    """BN-free, dropout-free model with the zoo's apply signature — isolates
    the accumulation math from per-micro-batch BN-stat semantics."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(32)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)


def _state_for(trainer, model, mesh):
    from distribuuuu_tpu.utils.optim import construct_optimizer

    state = trainer.create_train_state(model, jax.random.key(0), mesh, 8)
    return state, construct_optimizer()


def test_accum_matches_full_batch_step():
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.parallel import mesh as mesh_lib, sharding as sharding_lib

    config.reset_cfg()
    mesh = mesh_lib.build_mesh()
    model = _TinyMLP()

    rng = np.random.default_rng(0)
    batch = {
        "image": rng.standard_normal((32, 8, 8, 3)).astype(np.float32),
        "label": rng.integers(0, 10, size=(32,)).astype(np.int32),
        "mask": np.ones((32,), np.float32),
    }

    state, optimizer = _state_for(trainer, model, mesh)
    full = trainer.make_train_step(model, optimizer, topk=5)
    state_full, m_full = full(state, sharding_lib.shard_batch(mesh, batch))

    state2, _ = _state_for(trainer, model, mesh)
    acc = trainer.make_train_step(model, optimizer, topk=5, accum_steps=4)
    state_acc, m_acc = acc(
        state2, sharding_lib.shard_micro_batch(mesh, batch, accum=4)
    )

    for a, b in zip(
        jax.tree.leaves(jax.tree.map(np.asarray, state_full.params)),
        jax.tree.leaves(jax.tree.map(np.asarray, state_acc.params)),
    ):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    # mean of micro losses == full-batch loss (equal micro sizes)
    np.testing.assert_allclose(
        float(m_acc["loss"]), float(m_full["loss"]), rtol=1e-5
    )
    np.testing.assert_allclose(
        float(m_acc["top1"]), float(m_full["top1"]), rtol=1e-5
    )


def test_accum_rejects_indivisible_batch():
    from distribuuuu_tpu.parallel import mesh as mesh_lib, sharding as sharding_lib

    config.reset_cfg()
    mesh = mesh_lib.build_mesh()
    batch = {
        "image": np.zeros((16, 8, 8, 3), np.float32),
        "label": np.zeros((16,), np.int32),
        "mask": np.ones((16,), np.float32),
    }
    with pytest.raises(ValueError, match="not divisible"):
        sharding_lib.shard_micro_batch(mesh, batch, accum=3)


def test_train_model_fails_fast_on_indivisible_accum(tmp_path):
    from distribuuuu_tpu import trainer

    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.DUMMY_INPUT = True
    cfg.TRAIN.BATCH_SIZE = 2  # per-host 16 on the 8-device mesh
    cfg.TRAIN.IM_SIZE = 32
    cfg.TRAIN.GRAD_ACCUM_STEPS = 3  # 16 % 3 != 0 → refuse before compiling
    cfg.OUT_DIR = str(tmp_path)
    with pytest.raises(ValueError, match="GRAD_ACCUM_STEPS"):
        trainer.train_model()

    # divisible by accum but the micro-batch can't shard over data=8
    cfg.TRAIN.GRAD_ACCUM_STEPS = 16  # micro = 16/16 = 1 sample < 8 shards
    with pytest.raises(ValueError, match="data axis"):
        trainer.train_model()


def test_train_model_with_grad_accum(tmp_path):
    from distribuuuu_tpu import trainer

    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.DUMMY_INPUT = True
    cfg.OPTIM.MAX_EPOCH = 1
    cfg.TRAIN.BATCH_SIZE = 2
    cfg.TRAIN.IM_SIZE = 32
    cfg.TRAIN.PRINT_FREQ = 4
    cfg.TRAIN.GRAD_ACCUM_STEPS = 2  # 16-sample global batch → 2 micro-batches
    cfg.TEST.BATCH_SIZE = 4
    cfg.TEST.IM_SIZE = 32
    cfg.RNG_SEED = 1
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.OUT_DIR = str(tmp_path)
    best = trainer.train_model()
    assert best > 50.0
