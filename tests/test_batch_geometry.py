"""Startup batch-geometry validation (trainer.check_batch_geometry).

These constraints must fail BEFORE the expensive state init/compile — and
before any training happens. The eval-batch GPipe check exists because the
val loader pads every batch to the full TEST.BATCH_SIZE: an indivisible
eval batch used to train a whole epoch and then crash inside validate()
before that epoch's checkpoint was written (ADVICE r2, trainer.py).
No compiles happen here, so the file is fast-tier.
"""

import pytest

from distribuuuu_tpu import trainer
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.parallel import mesh as mesh_lib


def _vit_pipe_cfg(train_bs=8, test_bs=8, microbatch=4):
    cfg.MODEL.ARCH = "vit_tiny"
    cfg.TRAIN.BATCH_SIZE = train_bs  # per chip; ×8 local devices
    cfg.TEST.BATCH_SIZE = test_bs
    cfg.MESH.PIPE = 4
    cfg.MESH.DATA = -1  # → 2 on the 8-device mesh
    cfg.MESH.MICROBATCH = microbatch
    return mesh_lib.mesh_from_cfg(cfg)


def test_valid_pipe_geometry_passes():
    mesh = _vit_pipe_cfg()
    # per-shard train batch = 8*8/2 = 32, divisible by 4 microbatches
    assert trainer.check_batch_geometry(mesh) == 64


def test_train_batch_indivisible_by_microbatches_raises():
    mesh = _vit_pipe_cfg(train_bs=3, microbatch=8)  # per shard 12 % 8
    with pytest.raises(ValueError, match="GPipe microbatches"):
        trainer.check_batch_geometry(mesh)


def test_eval_batch_indivisible_by_microbatches_raises():
    # train side fine (32 % 4 == 0); eval per shard = 25*8/2 = 100 % 8 != 0
    mesh = _vit_pipe_cfg(train_bs=8, test_bs=25, microbatch=4)
    cfg.MESH.MICROBATCH = 8
    with pytest.raises(ValueError, match="eval batch"):
        trainer.check_batch_geometry(mesh)


def test_small_eval_batch_falls_back_no_error():
    """Below one microbatch-set per shard PipelinedViT runs its sequential
    fallback — startup must not reject it."""
    mesh = _vit_pipe_cfg(train_bs=8, test_bs=1, microbatch=4)
    cfg.MESH.MICROBATCH = 16  # eval per shard 4 < 16 → fallback, OK
    cfg.TRAIN.BATCH_SIZE = 16  # per shard 64 % 16 == 0
    trainer.check_batch_geometry(mesh)


def test_grad_accum_indivisible_raises():
    cfg.MODEL.ARCH = "resnet18"
    cfg.TRAIN.BATCH_SIZE = 3  # 24 per host
    cfg.TRAIN.GRAD_ACCUM_STEPS = 5
    mesh = mesh_lib.mesh_from_cfg(cfg)
    with pytest.raises(ValueError, match="GRAD_ACCUM_STEPS"):
        trainer.check_batch_geometry(mesh)


def test_ghost_bn_indivisible_raises():
    cfg.MODEL.ARCH = "resnet18"
    cfg.TRAIN.BATCH_SIZE = 8  # global 64
    cfg.MODEL.BN_GROUP = 48  # 64 > 48, 64 % 48 != 0
    mesh = mesh_lib.mesh_from_cfg(cfg)
    with pytest.raises(ValueError, match="ghost BN group"):
        trainer.check_batch_geometry(mesh)


def test_eval_only_skips_train_constraints():
    """ADVICE r3 #2: a train-invalid but eval-valid config must not block
    a pure evaluation — test_model() runs only the eval half."""
    mesh = _vit_pipe_cfg()
    # train-invalid: per-host batch 8*8=64 not divisible by accum 7
    cfg.TRAIN.GRAD_ACCUM_STEPS = 7
    with pytest.raises(ValueError, match="GRAD_ACCUM_STEPS"):
        trainer.check_batch_geometry(mesh)
    assert trainer.check_batch_geometry(mesh, eval_only=True) is None

    # but an eval-invalid config still fails in eval_only mode
    mesh = _vit_pipe_cfg(test_bs=3, microbatch=8)  # eval per shard 12 % 8
    with pytest.raises(ValueError, match="eval batch"):
        trainer.check_batch_geometry(mesh, eval_only=True)
