"""Test fixtures: run everything on a virtual 8-device CPU mesh.

This is the JAX analogue of the reference's "multi-node without a cluster"
trick (ref: /root/reference/README.md:119-144 — oversubscribing one node with
CUDA_VISIBLE_DEVICES partitions): XLA's host platform is told to expose 8
virtual CPU devices, so every sharding/collective path compiles and runs
without TPU hardware.
"""

import os

# Must be set before jax backends initialize. Force-override: the session
# env/sitecustomize may pin JAX_PLATFORMS to a real TPU tunnel (and does so
# via jax.config.update, which beats the env var) — tests run on the fake mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_configure(config):
    # session start stamp for the tier-1 wall-clock guard
    # (tests/test_zz_tier1_budget.py): the suite must fit its timeout
    # with margin, or the guard fails BEFORE the driver's `timeout` kills
    # the run with no diagnostics
    import time

    config._t1_start = time.monotonic()


@pytest.fixture(autouse=True)
def _reset_global_cfg():
    """Each test sees pristine config defaults."""
    from distribuuuu_tpu import config

    config.reset_cfg()
    yield
    config.reset_cfg()
