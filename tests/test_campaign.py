"""Traffic-campaign plane (distribuuuu_tpu/serve/campaign/, ISSUE 16):
campaign DSL strict validation, seeded-schedule determinism (same YAML +
seed ⇒ identical schedule, pinned against the committed artifact),
model-envelope framing, wrong-model-id refusal with the registered list,
deterministic SLO overflow rerouting over fake socket replicas with
degraded accounting, the three new serve alert-rule kinds, and the
quantized logits-delta pins — all toy fixtures, no replica processes.
"""

from __future__ import annotations

import glob
import json
import os
import socket
import threading

import numpy as np
import pytest
import yaml

from distribuuuu_tpu.serve import protocol
from distribuuuu_tpu.serve import quantize as quantize_lib
from distribuuuu_tpu.serve.campaign import (
    CampaignRunner,
    build_schedule,
    load_campaign,
    parse_campaign,
    schedule_hash,
)
from distribuuuu_tpu.serve.campaign import dsl
from distribuuuu_tpu.serve.fleet import Router
from distribuuuu_tpu.telemetry import live, schema

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CAMPAIGN_DIR = os.path.join(ROOT, "config", "campaigns")

OK_RESP = json.dumps(
    {"pred": 1, "topk": [1, 0], "logits": [0.0, 1.0]}
).encode()
BUSY_RESP = json.dumps(
    {"error": "queue_full", "retry_after_ms": 5.0}
).encode()


def _doc(**over) -> dict:
    doc = {
        "campaign": 1,
        "name": "toy",
        "seed": 7,
        "interval_s": 1.0,
        "models": [{"name": "a", "p99_slo_ms": 100.0}],
        "rules": [{"kind": "p99-breach", "threshold": 50.0}],
        "phases": [
            {"name": "control", "kind": "steady", "duration_s": 2,
             "rate_rps": 3, "expect": []},
        ],
    }
    doc.update(over)
    return doc


# -- DSL validation ----------------------------------------------------------

def test_parse_campaign_happy_path_and_mix_normalization():
    spec = parse_campaign(_doc(models=[
        {"name": "a"}, {"name": "b"},
    ], phases=[
        {"name": "p", "kind": "steady", "duration_s": 2, "rate_rps": 3,
         "expect": [], "mix": {"a": 3.0, "b": 1.0}},
    ]))
    assert spec.name == "toy" and spec.seed == 7
    assert spec.phases[0].mix == (("a", 0.75), ("b", 0.25))
    assert spec.duration_s == 2


@pytest.mark.parametrize("mutation, match", [
    ({"campaign": 2}, "campaign: 1"),
    ({"typo_key": 1}, "unknown campaign keys"),
    ({"models": []}, "at least one model"),
    ({"models": [{"name": "a", "bogus": 1}]}, "unknown model keys"),
    ({"models": [{"name": "a", "overflow_to": "ghost"}]}, "undeclared"),
    ({"phases": []}, "at least one phase"),
    ({"phases": [{"name": "p", "kind": "tsunami", "duration_s": 1,
                  "rate_rps": 1, "expect": []}]}, "unknown phase kind"),
    ({"phases": [{"name": "p", "kind": "steady", "duration_s": 1,
                  "rate_rps": 1, "expect": ["stall"]}]}, "un-armable"),
    ({"phases": [{"name": "p", "kind": "steady", "duration_s": 1,
                  "rate_rps": 1, "expect": ["backpressure"]}]},
     "arms only"),
    ({"phases": [{"name": "p", "kind": "rolling_update", "duration_s": 1,
                  "rate_rps": 1, "expect": []}]}, "update.model"),
    ({"phases": [{"name": "p", "kind": "steady", "duration_s": 1,
                  "rate_rps": 1, "expect": [], "mix": {"ghost": 1.0}}]},
     "unknown models"),
], ids=["version", "spec-key", "no-models", "model-key", "overflow-ghost",
        "no-phases", "phase-kind", "unarmable-expect", "unarmed-expect",
        "update-model", "mix-ghost"])
def test_parse_campaign_rejects(mutation, match):
    with pytest.raises(ValueError, match=match):
        parse_campaign(_doc(**mutation))


def test_campaign_rule_kinds_are_all_engine_evaluable():
    assert set(dsl.CAMPAIGN_RULE_KINDS) <= set(live.RULE_KINDS)


# -- schedule determinism ----------------------------------------------------

def test_build_schedule_deterministic_and_seed_sensitive():
    spec = parse_campaign(_doc(phases=[
        {"name": "ramp", "kind": "diurnal", "duration_s": 5, "rate_rps": 2,
         "peak_rps": 20, "expect": []},
        {"name": "tail", "kind": "heavy_tail", "duration_s": 5,
         "rate_rps": 4, "size_alpha": 1.1, "size_max": 6, "expect": []},
    ]))
    s1, s2 = build_schedule(spec), build_schedule(spec)
    assert s1 == s2 and schedule_hash(s1) == schedule_hash(s2)
    assert s1 == sorted(s1, key=lambda r: r[0])
    assert all(1 <= size <= 6 for _t, _m, size in s1)
    assert any(size > 1 for _t, _m, size in s1)  # the tail actually draws
    other = parse_campaign(_doc(seed=8, phases=[
        {"name": "ramp", "kind": "diurnal", "duration_s": 5, "rate_rps": 2,
         "peak_rps": 20, "expect": []},
        {"name": "tail", "kind": "heavy_tail", "duration_s": 5,
         "rate_rps": 4, "size_alpha": 1.1, "size_max": 6, "expect": []},
    ]))
    assert schedule_hash(build_schedule(other)) != schedule_hash(s1)


def test_flash_rate_curve_bursts_only_inside_window():
    spec = parse_campaign(_doc(phases=[
        {"name": "crowd", "kind": "flash", "duration_s": 10, "rate_rps": 2,
         "burst_x": 50, "burst_window": [0.4, 0.6], "expect": []},
    ]))
    phase = spec.phases[0]
    assert dsl._rate(phase, 0.1) == 2.0
    assert dsl._rate(phase, 0.5) == 100.0
    assert dsl._rate(phase, 0.7) == 2.0
    sched = build_schedule(spec)
    inside = sum(1 for t, _m, _s in sched if 4.0 <= t < 6.0)
    outside = len(sched) - inside
    assert inside > outside  # 20% of the time carries most of the load


def test_shipped_campaign_yamls_parse_and_schedule():
    paths = sorted(glob.glob(os.path.join(CAMPAIGN_DIR, "*.yaml")))
    assert len(paths) >= 4  # the committed campaign matrix
    names = set()
    for path in paths:
        spec = load_campaign(path)
        names.add(spec.name)
        sched = build_schedule(spec)
        assert sched, f"{path} schedules zero requests"
        assert schedule_hash(build_schedule(spec)) == schedule_hash(sched)
    assert "degrade_under_pressure" in names  # ISSUE 16 acceptance scenario


def test_committed_artifact_schedule_hashes_reproduce():
    """The determinism pin against the REAL archived run: rebuilding each
    campaign's schedule from its shipped YAML must give exactly the
    schedule_hash the committed SERVE_CAMPAIGN artifact recorded."""
    artifacts = sorted(glob.glob(os.path.join(ROOT, "SERVE_CAMPAIGN_r*.json")))
    if not artifacts:
        pytest.skip("no committed SERVE_CAMPAIGN artifact yet")
    doc = json.load(open(artifacts[-1]))
    by_name = {}
    for path in glob.glob(os.path.join(CAMPAIGN_DIR, "*.yaml")):
        spec = load_campaign(path)
        by_name[spec.name] = spec
    assert len(doc["campaigns"]) >= 4
    for c in doc["campaigns"]:
        spec = by_name[c["campaign"]]
        assert schedule_hash(build_schedule(spec)) == c["schedule_hash"], (
            f"campaign {c['campaign']}: shipped YAML no longer reproduces "
            f"the archived schedule — rerun tools/serve_campaign.py"
        )
        assert c["ok"], f"committed campaign {c['campaign']} is red"


def test_long_context_campaign_spec_and_payload_bank():
    """The long-context scenario (ISSUE 19c) stays coherent end to end:
    the shipped YAML arms backpressure + slo-breach and expects the
    surge (and ONLY the surge) to backpressure; the harness cfg keeps
    the reservation below the queue and the chunk aligned to the paged
    cache; and every heavy-tail bank prompt fits the chunked admission
    bound while both length classes stay represented."""
    import sys

    spec = load_campaign(os.path.join(CAMPAIGN_DIR, "long_context.yaml"))
    assert spec.name == "long_context"
    assert {r["kind"] for r in spec.rules} == {"backpressure", "slo-breach"}
    expects = {p.name: set(p.expect) for p in spec.phases}
    assert expects == {"control": set(), "long_surge": {"backpressure"},
                       "drain": set()}
    # the model row carries NO target: only the router's per-length-class
    # rows vote in the slo-breach rule (shorts-held-their-SLO evidence)
    assert spec.models[0]["p99_slo_ms"] is None
    assert schedule_hash(build_schedule(spec)) == schedule_hash(
        build_schedule(spec)
    )

    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import serve_campaign

        import distribuuuu_tpu.config as config
        try:
            cfg = serve_campaign.long_context_cfg("/tmp/lc_cfg_probe")
            threshold = cfg.SERVE.LONG_PROMPT_THRESHOLD
            assert threshold >= 1
            assert 0 < cfg.SERVE.LONG_MAX_QUEUE < cfg.SERVE.MAX_QUEUE
            assert cfg.GENERATE.CACHE_TILES[-1] % cfg.GENERATE.CHUNK_PREFILL == 0
            cache_cap = cfg.GENERATE.CACHE_TILES[-1]
            max_new_cap = cfg.GENERATE.MAX_NEW_TOKENS
        finally:
            config.reset_cfg()
        bank = serve_campaign.lm_long_payload_bank()
        assert bank == serve_campaign.lm_long_payload_bank()  # deterministic
        classes = set()
        for frame in bank:
            ctrl = protocol.parse_ctrl(frame)
            assert ctrl["op"] == "generate"
            plen = len(ctrl["tokens"])
            # the chunked paged-prefill admission bound: the whole
            # stream (prompt + budget) fits the largest cache tile
            assert plen + min(ctrl["max_new_tokens"], max_new_cap) <= cache_cap
            classes.add("long" if plen >= threshold else "short")
        assert classes == {"short", "long"}  # heavy tail drew both
    finally:
        sys.path.remove(os.path.join(ROOT, "tools"))


def test_committed_long_context_artifact_has_starvation_evidence():
    """Against the REAL archived run: the long class bounced off the
    admission reservation (its rejections are the backpressure evidence)
    while the short class held its windowed p99 SLO."""
    artifacts = sorted(glob.glob(os.path.join(ROOT, "SERVE_CAMPAIGN_r*.json")))
    if not artifacts:
        pytest.skip("no committed SERVE_CAMPAIGN artifact yet")
    doc = json.load(open(artifacts[-1]))
    lc = next((c for c in doc["campaigns"]
               if c["campaign"] == "long_context"), None)
    if lc is None:
        pytest.skip("latest artifact predates the long_context campaign")
    assert lc["ok"] and lc["control_clean"]
    classes = lc["length_classes"]
    assert lc["long_prompt_threshold"] >= 1
    assert classes["long"]["rejected"] > 0  # longs hit the reservation
    assert classes["short"]["requests"] > 0
    assert classes["short"]["p99_ms"] < classes["short"]["p99_slo_ms"]


# -- model envelope ----------------------------------------------------------

def test_model_envelope_roundtrip_and_bare_passthrough():
    payload = b"\x93NUMPYfake-image-bytes"
    frame = protocol.model_envelope("resnet18", payload)
    assert frame.startswith(protocol.MODEL_MAGIC)
    assert protocol.split_model_envelope(frame) == ("resnet18", payload)
    # bare payloads pass through untouched (single-model clients)
    assert protocol.split_model_envelope(payload) == (None, payload)
    # a ctrl frame is NOT a model envelope (magics differ at byte 5)
    ctrl = protocol.ctrl_request("stats")
    assert protocol.split_model_envelope(ctrl) == (None, ctrl)
    with pytest.raises(ValueError, match="1..255"):
        protocol.model_envelope("", payload)
    with pytest.raises(ValueError, match="truncated"):
        protocol.split_model_envelope(protocol.MODEL_MAGIC + bytes([9]) + b"ab")


# -- fake socket replicas ----------------------------------------------------

class FakeReplica:
    """Real localhost socket speaking the serve framing with a scripted
    responder — the no-process fleet fixture (tests/test_fleet.py idiom)."""

    def __init__(self, responder):
        self.responder = responder
        self.listener = protocol.open_listener("127.0.0.1", 0)
        self.port = self.listener.getsockname()[1]
        self.requests = 0
        self._stop = threading.Event()
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        self.listener.settimeout(0.05)
        while not self._stop.is_set():
            try:
                conn, _ = self.listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._handle, args=(conn,), daemon=True
            ).start()

    def _handle(self, conn):
        with conn:
            while True:
                try:
                    payload = protocol.recv_frame(conn)
                except (OSError, ValueError):
                    return
                if payload is None:
                    return
                self.requests += 1
                try:
                    protocol.send_frame(conn, self.responder(payload))
                except OSError:
                    return

    def close(self):
        self._stop.set()
        self.listener.close()


def _multi_model_router(premium, economy) -> Router:
    router = Router(request_timeout_s=5.0)
    router.register_model(
        "resnet50", slo_class="premium", p99_slo_ms=300.0,
        overflow_to="resnet18",
    )
    router.register_model("resnet18", slo_class="economy", p99_slo_ms=600.0)
    for srv, model in ((premium, "resnet50"), (economy, "resnet18")):
        rep = router.add_replica("127.0.0.1", srv.port, model=model)
        router.mark_routable(rep.id)
    return router


def test_unknown_model_refused_with_registered_list():
    premium = FakeReplica(lambda p: OK_RESP)
    economy = FakeReplica(lambda p: OK_RESP)
    try:
        router = _multi_model_router(premium, economy)
        resp = json.loads(router.dispatch(
            protocol.model_envelope("resnet152", b"img")
        ))
        assert resp["error"] == "unknown_model"
        assert resp["model"] == "resnet152"
        assert resp["models"] == ["resnet18", "resnet50"]
        # refused before any replica saw a byte
        assert premium.requests == 0 and economy.requests == 0
        assert router.stats()["unknown_model"] == 1
    finally:
        premium.close()
        economy.close()


def test_model_routing_is_model_exclusive():
    """Each model's traffic lands ONLY on its own replicas, even when the
    other pool is idle (no silent cross-model leakage)."""
    premium = FakeReplica(lambda p: OK_RESP)
    economy = FakeReplica(lambda p: OK_RESP)
    try:
        router = _multi_model_router(premium, economy)
        for _ in range(4):
            resp = router.dispatch(protocol.model_envelope("resnet18", b"x"))
            assert json.loads(resp)["pred"] == 1
        assert economy.requests == 4 and premium.requests == 0
        st = router.stats()
        assert st["models"]["resnet18"]["requests"] == 4
        assert st["models"]["resnet50"]["requests"] == 0
        assert [p["model"] for p in st["per_replica"]] == [
            "resnet50", "resnet18",
        ]
    finally:
        premium.close()
        economy.close()


def test_slo_overflow_reroutes_to_cheap_model_deterministically():
    """ISSUE 16 tentpole (a): every premium replica saturated ⇒ the
    stripped payload spills to the overflow_to model; the answer comes
    back and BOTH sides' degraded counters record it. Repeatable: same
    saturation, same spill, every time."""
    premium = FakeReplica(lambda p: BUSY_RESP)   # always saturated
    economy = FakeReplica(lambda p: OK_RESP)     # always absorbs
    try:
        router = _multi_model_router(premium, economy)
        for i in range(3):
            resp = json.loads(router.dispatch(
                protocol.model_envelope("resnet50", b"img")
            ))
            assert resp.get("pred") == 1, resp  # the economy answer, not busy
        st = router.stats()
        assert st["degraded"] == 3
        assert st["models"]["resnet50"]["degraded_out"] == 3
        assert st["models"]["resnet18"]["degraded_in"] == 3
        assert st["models"]["resnet50"]["rejected"] == 0  # spill ≠ reject
        # economy served every spill; premium only ever answered busy
        assert economy.requests == 3
    finally:
        premium.close()
        economy.close()


def test_saturation_without_overflow_passes_busy_verbatim():
    """A model with NO overflow_to keeps the verbatim-backpressure
    contract: the client sees the replica's own retry-after rejection."""
    premium = FakeReplica(lambda p: BUSY_RESP)
    economy = FakeReplica(lambda p: BUSY_RESP)
    try:
        router = _multi_model_router(premium, economy)
        resp = router.dispatch(protocol.model_envelope("resnet18", b"img"))
        assert resp == BUSY_RESP
        st = router.stats()
        assert st["models"]["resnet18"]["rejected"] == 1
        assert st["degraded"] == 0
    finally:
        premium.close()
        economy.close()


def test_runner_snapshot_is_rule_engine_compatible():
    """The runner's serve-shaped snapshot feeds RuleEngine.evaluate
    without KeyError for every campaign-armable kind."""
    premium = FakeReplica(lambda p: OK_RESP)
    economy = FakeReplica(lambda p: OK_RESP)
    try:
        router = _multi_model_router(premium, economy)
        router.dispatch(protocol.model_envelope("resnet50", b"img"))
        spec = parse_campaign(_doc(rules=[
            {"kind": k, "threshold": 1e9}
            for k in dsl.CAMPAIGN_RULE_KINDS
        ]))
        runner = CampaignRunner(
            spec, router, payload_for=lambda m: b"img"
        )
        snap = runner._snapshot()
        assert snap["totals"]["steps"] == 1
        assert set(snap["serve"]["models"]) == {"resnet18", "resnet50"}
        engine = live.RuleEngine(
            [live.AlertRule(dict(r)) for r in spec.rules], spec.interval_s
        )
        assert engine.evaluate(snap) == []  # thresholds unreachable: calm
        runner._pool.shutdown(wait=False)
    finally:
        premium.close()
        economy.close()


# -- the three new alert-rule kinds ------------------------------------------

def _snap(steps, serve):
    return {"schema": 1, "steps": steps, "totals": {"steps": steps},
            "compiles": {"count": 0},
            "events": {"stall": 0, "nonfinite": 0}, "serve": serve}


def test_backpressure_rule_fires_on_rejected_growth():
    engine = live.RuleEngine(
        [live.AlertRule({"kind": "backpressure", "threshold": 10,
                         "window_s": 2})], interval_s=1.0,
    )
    base = {"p99_ms": 5.0, "window_samples": 9, "queue_depth": 0}
    # one serve snapshot: no delta to form yet — insufficient signal
    assert engine.evaluate(_snap(1, {**base, "rejected": 0})) == []
    # +20 rejected across the window ≥ threshold 10: fires
    fired = engine.evaluate(_snap(2, {**base, "rejected": 20}))
    assert [f["rule"] for f in fired] == ["backpressure"]
    assert fired[0]["value"] == 20.0


def test_p99_breach_alert_names_exemplar_traces():
    """Exemplar attribution (ISSUE 20): a breaching window that carries
    traced samples lands their ids on the alert as exemplar_trace_ids
    (worst ≤ 3); an untraced window fires the same alert WITHOUT the
    key — tracing never changes scoring, only attribution."""
    base = {"window_samples": 9, "queue_depth": 0, "rejected": 0}
    exs = [
        {"trace": f"{i:016x}", "latency_ms": 900.0 - i} for i in range(3)
    ]
    engine = live.RuleEngine(
        [live.AlertRule({"kind": "p99-breach", "threshold": 50.0})],
        interval_s=1.0,
    )
    fired = engine.evaluate(
        _snap(1, {**base, "p99_ms": 500.0, "exemplars": exs})
    )
    assert [f["rule"] for f in fired] == ["p99-breach"]
    assert fired[0]["exemplar_trace_ids"] == [e["trace"] for e in exs]
    # the alert record stays schema-valid with the free-form extra
    schema.check_fields(
        "alert", {"kind", "rank", "t", *fired[0].keys()}
    )
    # untraced window: same verdict, no attribution key
    engine = live.RuleEngine(
        [live.AlertRule({"kind": "p99-breach", "threshold": 50.0})],
        interval_s=1.0,
    )
    fired = engine.evaluate(_snap(1, {**base, "p99_ms": 500.0}))
    assert [f["rule"] for f in fired] == ["p99-breach"]
    assert "exemplar_trace_ids" not in fired[0]


def test_backpressure_alert_names_exemplar_traces():
    engine = live.RuleEngine(
        [live.AlertRule({"kind": "backpressure", "threshold": 10,
                         "window_s": 2})], interval_s=1.0,
    )
    base = {"p99_ms": 5.0, "window_samples": 9, "queue_depth": 0}
    exs = [{"trace": "ee" * 8, "latency_ms": 750.0}]
    assert engine.evaluate(_snap(1, {**base, "rejected": 0})) == []
    fired = engine.evaluate(
        _snap(2, {**base, "rejected": 20, "exemplars": exs})
    )
    assert [f["rule"] for f in fired] == ["backpressure"]
    assert fired[0]["exemplar_trace_ids"] == ["ee" * 8]


def test_degrade_spill_rule_fires_on_degraded_growth():
    engine = live.RuleEngine(
        [live.AlertRule({"kind": "degrade-spill", "threshold": 5,
                         "window_s": 2})], interval_s=1.0,
    )
    base = {"p99_ms": 5.0, "window_samples": 9, "queue_depth": 0,
            "rejected": 0}
    assert engine.evaluate(_snap(1, {**base, "degraded": 0})) == []
    assert engine.evaluate(_snap(2, {**base, "degraded": 3})) == []  # < 5
    fired = engine.evaluate(_snap(3, {**base, "degraded": 9}))
    assert [f["rule"] for f in fired] == ["degrade-spill"]


def test_slo_breach_rule_reads_per_model_ratio():
    engine = live.RuleEngine(
        [live.AlertRule({"kind": "slo-breach", "threshold": 1.2,
                         "min_steps": 4})], interval_s=1.0,
    )
    base = {"p99_ms": 5.0, "window_samples": 9, "queue_depth": 0,
            "rejected": 0}
    # under target: calm
    calm = {"m": {"samples": 8, "p99_ms": 100.0, "target_ms": 300.0}}
    assert engine.evaluate(_snap(1, {**base, "models": calm})) == []
    # over target but too few samples: insufficient signal, not a breach
    thin = {"m": {"samples": 2, "p99_ms": 900.0, "target_ms": 300.0}}
    assert engine.evaluate(_snap(2, {**base, "models": thin})) == []
    # a model with no target never votes
    untargeted = {"m": {"samples": 50, "p99_ms": 900.0, "target_ms": None}}
    assert engine.evaluate(_snap(3, {**base, "models": untargeted})) == []
    # 450/300 = 1.5x ≥ 1.2x: fires with the ratio as the value
    hot = {"m": {"samples": 8, "p99_ms": 450.0, "target_ms": 300.0}}
    fired = engine.evaluate(_snap(4, {**base, "models": hot}))
    assert [f["rule"] for f in fired] == ["slo-breach"]
    assert fired[0]["value"] == 1.5


def test_new_kinds_declared_everywhere():
    # telemetry schema carries the four new kinds with required fields
    assert schema.KINDS["campaign.phase"] >= {"campaign", "phase", "ok"}
    assert schema.KINDS["campaign.verdict"] >= {"campaign", "ok"}
    assert schema.KINDS["fleet.model_route"] >= {"model", "requests"}
    assert schema.KINDS["serve.quantized"] >= {"arch", "mode"}
    # and the shipped monitor rules file declares every engine kind
    # (dormant where a baseline/serve peer is needed) — same pin shape
    # as tests/test_monitor.py's
    doc = yaml.safe_load(open(os.path.join(ROOT, "config",
                                           "monitor_rules.yaml")))
    declared = {r["kind"] for r in doc["rules"]}
    assert {"backpressure", "slo-breach", "degrade-spill"} <= declared


# -- quantized variants ------------------------------------------------------

def _toy_model_and_variables():
    import flax.linen as nn
    import jax

    class Toy(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(32)(x)   # (48, 32) kernel: int8-eligible (≥256)
            x = nn.relu(x)
            return nn.Dense(4)(x)  # (32, 4): too small, stays f32

    model = Toy()
    variables = model.init(
        jax.random.key(0), np.zeros((1, 4, 4, 3), np.float32)
    )
    return model, {"params": variables["params"]}


def test_quantize_variables_packs_and_dequantizes():
    model, variables = _toy_model_and_variables()
    packed, meta = quantize_lib.quantize_variables(variables, "int8")
    assert meta["mode"] == "int8"
    assert meta["quantized_leaves"] >= 1
    assert meta["bytes_after"] < meta["bytes_before"]
    # the big kernel became an int8 payload with per-output-axis scales
    q = packed["params"]["Dense_0"]["kernel"]
    assert q["q8"].dtype == np.int8 and q["q8"].shape == (48, 32)
    assert q["q8_scale"].shape == (1, 32)  # keepdims broadcast scales
    # the small kernel stayed float
    small = packed["params"]["Dense_1"]["kernel"]
    assert not isinstance(small, dict)
    # in-graph dequant restores an apply-able tree
    restored = quantize_lib.dequantize_in_graph(packed)
    x = np.random.default_rng(0).standard_normal(
        (2, 4, 4, 3)
    ).astype(np.float32)
    ref = model.apply(variables, x, train=False)
    got = model.apply(restored, x, train=False)
    assert np.asarray(got).shape == np.asarray(ref).shape


@pytest.mark.parametrize("mode", ["bf16", "int8"])
def test_quantized_logits_delta_within_tolerance(mode):
    """ISSUE 16 tentpole (b) pin: the quantized variant's logits stay
    within the mode's declared tolerance of f32 on a seeded batch."""
    model, variables = _toy_model_and_variables()
    images = np.random.default_rng(1).standard_normal(
        (8, 4, 4, 3)
    ).astype(np.float32)
    rep = quantize_lib.quantized_delta(model, variables, images, mode)
    assert rep["mode"] == mode
    assert rep["tolerance"] == quantize_lib.TOLERANCE[mode]
    assert rep["rel_logits_delta"] <= rep["tolerance"], rep
    assert rep["ok"]
    assert rep["top1_agree"] >= 0.75


def test_quantize_rejects_unknown_mode():
    _model, variables = _toy_model_and_variables()
    with pytest.raises(ValueError, match="bf16"):
        quantize_lib.quantize_variables(variables, "fp4")


def test_engine_quantized_serving_same_buckets(tmp_path):
    """ISSUE 16 acceptance: quantized bucket variants serve through the
    UNCHANGED engine protocol — same buckets, same AOT compile count,
    logits within tolerance of the f32 engine."""
    from distribuuuu_tpu.serve.engine import Engine

    model, variables = _toy_model_and_variables()
    img = np.random.default_rng(2).standard_normal(
        (4, 4, 3)
    ).astype(np.float32)
    with Engine(model, variables, 4, max_batch=2, max_wait_ms=1.0,
                input_dtype=np.float32, quantize="") as ref_eng:
        ref = ref_eng.submit(img).result()
    with Engine(model, variables, 4, max_batch=2, max_wait_ms=1.0,
                input_dtype=np.float32, quantize="int8") as q_eng:
        assert q_eng.buckets == [1, 2]
        assert q_eng.n_compiles == 2  # bucket set unchanged by the variant
        assert q_eng.quantize_meta["mode"] == "int8"
        assert q_eng.stats()["quantize"] == "int8"
        got = q_eng.submit(img).result()
    denom = max(float(np.max(np.abs(ref))), 1e-9)
    delta = float(np.max(np.abs(got - ref))) / denom
    assert delta <= quantize_lib.TOLERANCE["int8"], delta


def test_shared_router_pools_stay_model_scoped():
    """Two PoolManagers share ONE router (the multi-model fleet shape):
    each must count, spawn, and drain only ITS OWN model's replicas.
    Regression: the second pool used to see the first pool's replica in
    the shared router, conclude its target was met, and never spawn —
    leaving the overflow model with zero replicas during the
    degrade-under-pressure campaign."""
    from distribuuuu_tpu.serve.fleet.pool import PoolManager

    warm = {"buckets": [1], "n_compiles": 1, "queue_depth": 0,
            "batch_occupancy": 0.0, "jit_compiles": 1}

    class Handle:
        pid = 1

        def __init__(self):
            self._rc = None

        def poll(self):
            return self._rc

        def terminate(self):
            self._rc = 0

        def kill(self):
            self._rc = -9

        def wait(self, timeout=None):
            return self._rc

    router = Router()
    pools = {}
    for name in ("premium", "economy"):
        pools[name] = PoolManager(
            router, lambda rid, port: Handle(),
            probe=lambda addr: dict(warm), model=name, min_replicas=0,
            warmup_timeout_s=2.0, warmup_poll_s=0.005,
            health_period_s=0.05,
        )
    pools["premium"].set_target(1)
    pools["premium"]._spawn_toward_target()
    assert pools["premium"]._wait_routable(1)
    # the second pool must STILL spawn toward its own target
    pools["economy"].set_target(1)
    assert len(pools["economy"]._spawn_toward_target()) == 1
    assert pools["economy"]._wait_routable(1)
    assert {r.model for r in router.replicas()} == {"premium", "economy"}
    # shutdown drains only this pool's replica off the shared router
    pools["economy"].shutdown(timeout=2.0)
    assert [r.model for r in router.replicas()] == ["premium"]
    pools["premium"].shutdown(timeout=2.0)
    assert router.replicas() == []
