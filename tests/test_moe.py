"""Expert-parallel MoE: both distributed strategies vs the dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distribuuuu_tpu.ops import moe
from distribuuuu_tpu.parallel import mesh as mesh_lib

D, F, E, T = 16, 32, 8, 64


@pytest.fixture(scope="module")
def setup():
    params = moe.init_moe_params(jax.random.key(0), D, F, E)
    x = jnp.asarray(
        np.random.default_rng(1).standard_normal((T, D)), jnp.float32
    )
    mesh = mesh_lib.build_mesh(data=1, model=8, seq=1, pipe=1)
    return params, x, mesh


def test_gating_weights_normalized(setup):
    params, x, _ = setup
    w, idx = moe.top_k_gating(x, params["gate"], top_k=2)
    np.testing.assert_allclose(np.asarray(w.sum(-1)), 1.0, rtol=1e-5)
    assert int(idx.min()) >= 0 and int(idx.max()) < E
    # top-1 of each row is the argmax of the gate softmax
    logits = x @ params["gate"]
    np.testing.assert_array_equal(np.asarray(idx[:, 0]), np.argmax(logits, -1))


@pytest.mark.parametrize("top_k", [1, 2])
def test_partial_matches_reference(setup, top_k):
    params, x, mesh = setup
    want = moe.moe_ffn_reference(params, x, top_k=top_k)
    got = jax.jit(
        lambda p, x: moe.moe_ffn_partial(p, x, mesh=mesh, top_k=top_k)
    )(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


@pytest.mark.parametrize("top_k", [1, 2])
def test_dispatch_matches_reference_with_ample_capacity(setup, top_k):
    params, x, mesh = setup
    want = moe.moe_ffn_reference(params, x, top_k=top_k)
    # capacity ≥ every token on one expert ⇒ nothing can drop
    got = jax.jit(
        lambda p, x: moe.moe_ffn_dispatch(
            p, x, mesh=mesh, top_k=top_k, capacity_factor=float(E)
        )
    )(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_dispatch_tight_capacity_matches_masked_reference(setup):
    """Tight capacity must equal the reference with exactly the over-capacity
    (token, expert) assignments zeroed — same slotting rule, computed here
    independently in numpy."""
    params, x, mesh = setup
    top_k, cf = 2, 0.5
    n = mesh.shape["model"]
    T_local = T // n
    C = max(1, int(np.ceil(T_local * top_k / E * cf)))

    out = jax.jit(
        lambda p, x: moe.moe_ffn_dispatch(
            p, x, mesh=mesh, top_k=top_k, capacity_factor=cf
        )
    )(params, x)
    assert out.shape == x.shape

    # independent slotting: per token-shard, count assignments per expert in
    # token order; an assignment survives while its expert has free slots
    weights, indices = moe.top_k_gating(x, params["gate"], top_k)
    weights, indices = np.asarray(weights), np.asarray(indices)
    keep = np.zeros((T, top_k), bool)
    for r in range(n):
        counts = np.zeros(E, int)
        for t in range(r * T_local, (r + 1) * T_local):
            for k in range(top_k):
                e = indices[t, k]
                if counts[e] < C:
                    keep[t, k] = True
                counts[e] += 1

    want = np.zeros_like(np.asarray(x))
    for t in range(T):
        for k in range(top_k):
            if not keep[t, k]:
                continue
            e = indices[t, k]
            y = moe._expert_ffn(
                params["w_in"][e], params["b_in"][e],
                params["w_out"][e], params["b_out"][e], x[t][None],
            )[0]
            want[t] += weights[t, k] * np.asarray(y)
    np.testing.assert_allclose(np.asarray(out), want, atol=1e-5)
    # the tight capacity really did drop something (else this test is vacuous)
    assert not keep.all()


@pytest.fixture(scope="module")
def batched_setup():
    """data×model mesh + a [B, S, d] activation whose per-data-shard token
    count (2·9=18) does NOT divide the model axis (4) — exercises the
    pad-token path of the batched dispatch."""
    params = moe.init_moe_params(jax.random.key(2), D, F, E)
    x = jnp.asarray(
        np.random.default_rng(5).standard_normal((4, 9, D)), jnp.float32
    )
    mesh = mesh_lib.build_mesh(data=2, model=4, seq=1, pipe=1)
    return params, x, mesh


@pytest.mark.slow  # heavy numeric sweep; dispatch exactness also pinned in slow tier
def test_dispatch_batched_matches_partial_at_ample_capacity(batched_setup):
    params, x, mesh = batched_setup
    want = moe.moe_ffn_partial_batched(params, x, mesh=mesh, top_k=2)
    out, dropped = jax.jit(
        lambda p, x: moe.moe_ffn_dispatch_batched(
            p, x, mesh=mesh, top_k=2, capacity_factor=float(E)
        )
    )(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), atol=1e-5)
    assert float(dropped) == 0.0


def test_dispatch_batched_tight_capacity_drops(batched_setup):
    params, x, mesh = batched_setup
    out, dropped = jax.jit(
        lambda p, x: moe.moe_ffn_dispatch_batched(
            p, x, mesh=mesh, top_k=2, capacity_factor=0.25
        )
    )(params, x)
    assert np.isfinite(np.asarray(out)).all()
    assert 0.0 < float(dropped) < 1.0, float(dropped)


def test_dispatch_batched_differentiable(batched_setup):
    params, x, mesh = batched_setup

    def loss(p):
        out, _ = moe.moe_ffn_dispatch_batched(
            p, x, mesh=mesh, top_k=2, capacity_factor=2.0
        )
        return jnp.mean(out**2)

    grads = jax.jit(jax.grad(loss))(params)
    norms = {k: float(jnp.linalg.norm(v)) for k, v in grads.items()}
    for k in ("w_in", "w_out", "gate"):
        assert norms[k] > 0, f"zero grad for {k}: {norms}"


def test_partial_path_is_differentiable(setup):
    params, x, mesh = setup

    def loss(p):
        return jnp.mean(moe.moe_ffn_partial(p, x, mesh=mesh, top_k=2) ** 2)

    grads = jax.jit(jax.grad(loss))(params)
    norms = {k: float(jnp.linalg.norm(v)) for k, v in grads.items()}
    for k in ("w_in", "w_out", "gate"):
        assert norms[k] > 0, f"zero grad for {k}: {norms}"


def test_params_sharding_places_expert_dim(setup):
    params, _, mesh = setup
    shardings = moe.moe_params_sharding(mesh, params)
    placed = jax.device_put(params, shardings)
    assert placed["w_in"].sharding.spec[0] == "model"
    shapes = {s.data.shape for s in placed["w_in"].addressable_shards}
    assert shapes == {(1, D, F)}
    assert placed["gate"].sharding.spec == ()


def test_load_balancing_loss_uniform_vs_collapsed(setup):
    params, x, _ = setup
    # near-uniform router: loss ≈ 1
    uniform_gate = jnp.zeros_like(params["gate"])
    near = float(moe.load_balancing_loss(x, uniform_gate, top_k=2))
    assert abs(near - 1.0) < 0.05, near
    # collapsed router: all-ones input with a strong positive column 0 gate
    # routes every token to expert 0 → loss → E
    strong = jnp.zeros_like(params["gate"]).at[:, 0].set(1.0)
    ones = jnp.ones_like(x)
    bad = float(moe.load_balancing_loss(ones, strong, top_k=1))
    assert bad > E * 0.9, bad
    # differentiable w.r.t. the gate
    g = jax.grad(lambda gw: moe.load_balancing_loss(x, gw, top_k=2))(
        params["gate"]
    )
    assert float(jnp.linalg.norm(g)) > 0
