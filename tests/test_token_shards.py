"""Token shard species (ISSUE 12): pack→read round-trip over the shared
shard container, species guards, config-drift refusals, and the exact
mid-epoch resume trajectory pin through the unchanged Loader cursor
protocol."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.data.shards import tokens as token_shards
from distribuuuu_tpu.data.shards.format import ShardFormatError
from distribuuuu_tpu.data.shards.tokens import TokenShardDataset
from distribuuuu_tpu.lm.tokenizer import ByteTokenizer

PACK = 16


def _docs(n=10, words=40, seed=0):
    rng = np.random.default_rng(seed)
    return [
        " ".join(f"w{rng.integers(0, 50)}" for _ in range(words)).encode()
        for _ in range(n)
    ]


@pytest.fixture()
def pack_dir(tmp_path):
    split = tmp_path / "train"
    token_shards.write_token_shards(
        str(split),
        token_shards.pack_token_stream(_docs(), PACK),
        PACK, target_bytes=2048,  # small target → several shards
    )
    return tmp_path


def test_pack_read_roundtrip_byte_identical(pack_dir):
    """Every packed sequence reads back exactly as the windowed token
    stream the packer cut — across shard boundaries."""
    tok = ByteTokenizer()
    stream = []
    for d in _docs():
        stream.extend(int(t) for t in tok.encode(d))
        stream.append(tok.eos_id)
    ds = TokenShardDataset(str(pack_dir), "train", seq_len=PACK)
    n = len(ds)
    assert n == len(stream) // (PACK + 1)
    assert len(ds.manifest["shards"]) > 1  # the small target really rolled
    for i in range(n):
        want = np.asarray(stream[i * (PACK + 1): (i + 1) * (PACK + 1)],
                          np.uint16)
        np.testing.assert_array_equal(ds.seq_tokens(i), want)
        x, y = ds[i]
        np.testing.assert_array_equal(x, want[:-1].astype(np.int32))
        np.testing.assert_array_equal(y, want[1:].astype(np.int32))


def test_species_guards_both_directions(pack_dir, tmp_path):
    """The image reader refuses a token pack (and the token reader an
    image pack) with the actionable species message."""
    from distribuuuu_tpu.data.shards.format import (
        ShardWriter, write_shard_manifest,
    )
    from distribuuuu_tpu.data.shards.reader import ShardDataset

    with pytest.raises(ShardFormatError, match="holds 'tokens' shards"):
        ShardDataset(str(pack_dir), "train", im_size=8, train=True)
    # a (fake) image pack under the token reader
    img_split = tmp_path / "imgpack" / "train"
    w = ShardWriter(str(img_split))
    w.add(b"\xff\xd8fakejpeg", 0, "a.jpg")
    write_shard_manifest(str(img_split), w.close(), ["cls"], 1024)
    with pytest.raises(ShardFormatError, match="holds 'images' shards"):
        TokenShardDataset(str(tmp_path / "imgpack"), "train", seq_len=PACK)


def test_config_drift_refusals(pack_dir):
    with pytest.raises(ShardFormatError, match="LM.SEQ_LEN"):
        TokenShardDataset(str(pack_dir), "train", seq_len=PACK * 2)
    with pytest.raises(ShardFormatError, match="NUM_CLASSES"):
        TokenShardDataset(str(pack_dir), "train", seq_len=PACK,
                          num_classes=100)
    # tokenizer identity drift: doctor the manifest
    import json
    import os

    man_path = os.path.join(str(pack_dir), "train", "MANIFEST.json")
    with open(man_path) as f:
        man = json.load(f)
    man["tokenizer"] = "bpe-v9"
    with open(man_path, "w") as f:
        json.dump(man, f)
    with pytest.raises(ShardFormatError, match="tokenizer identity drift"):
        TokenShardDataset(str(pack_dir), "train", seq_len=PACK)


def _token_loader(root, batch=2):
    from distribuuuu_tpu.data import construct_train_loader

    cfg.DATA.FORMAT = "tokens"
    cfg.LM.SEQ_LEN = PACK
    cfg.MODEL.NUM_CLASSES = 320
    cfg.TRAIN.DATASET = str(root)
    cfg.TRAIN.BATCH_SIZE = batch
    return construct_train_loader()


def test_loader_token_batches_int32(pack_dir):
    loader = _token_loader(pack_dir, batch=2)
    loader.set_epoch(0)
    b = next(iter(loader))
    n = b["image"].shape[0]  # per-host batch = per-chip x local devices
    assert b["image"].shape == (n, PACK) and b["image"].dtype == np.int32
    assert b["label"].shape == (n, PACK) and b["label"].dtype == np.int32
    assert b["mask"].shape == (n,)
    # next-token shift holds batch-wide
    np.testing.assert_array_equal(b["image"][:, 1:], b["label"][:, :-1])


def test_exact_midepoch_resume_cursor_roundtrip(pack_dir):
    """Loader-level pin: consume k batches, save the cursor, restore into
    a FRESH loader — iteration continues with exactly the batches the
    uninterrupted epoch would have produced."""
    loader = _token_loader(pack_dir, batch=1)
    assert loader.can_save_state()
    loader.set_epoch(2)
    full = [b["image"].copy() for b in loader]
    k = 3
    sd = loader.state_dict(k)
    assert sd["dataset_identity"]["tokenizer"] == "byte-v1"
    fresh = _token_loader(pack_dir, batch=1)
    skip = fresh.load_state_dict(sd)
    assert skip == k
    fresh.set_epoch(2)
    resumed = [b["image"].copy() for b in fresh]
    assert len(resumed) == len(full) - k
    for a, b in zip(resumed, full[k:]):
        np.testing.assert_array_equal(a, b)


def test_cursor_refused_on_identity_drift(pack_dir, tmp_path):
    """A cursor saved against one pack/tokenizer must not survive onto a
    different pack geometry — the loader degrades to epoch-granular
    resume with the reason."""
    loader = _token_loader(pack_dir, batch=1)
    loader.set_epoch(0)
    sd = loader.state_dict(2)
    sd["dataset_identity"] = dict(sd["dataset_identity"], pack_len=PACK * 2)
    fresh = _token_loader(pack_dir, batch=1)
    with pytest.raises(ValueError, match="dataset identity changed"):
        fresh.load_state_dict(sd)


LONG_PACK = 4096


def _long_pack(root, n_records=6, seed=3):
    """A pack_len=4096 split with a handful of records — pre-tokenized
    uint16 docs (pack_token_stream takes arrays verbatim), so the 4k
    geometry is real while the test stays toy-sized."""
    rng = np.random.default_rng(seed)
    docs = [
        rng.integers(0, 256, ((LONG_PACK + 1) * n_records // 3,))
        .astype(np.uint16)
        for _ in range(4)  # 4 docs → > n_records complete windows
    ]
    split = root / "train"
    token_shards.write_token_shards(
        str(split),
        token_shards.pack_token_stream(docs, LONG_PACK),
        LONG_PACK,
    )
    return root


def test_long_pack_roundtrip_and_exact_resume(tmp_path):
    """ISSUE 19 data plane: the shard container and the Loader's exact
    mid-epoch cursor hold at long-context pack geometry (pack_len=4096 —
    8 KiB records) exactly as at pack_len=16: byte-identical read-back,
    identity riding the cursor, resume producing the uninterrupted
    tail."""
    _long_pack(tmp_path)
    ds = TokenShardDataset(str(tmp_path), "train", seq_len=LONG_PACK)
    assert len(ds) >= 4
    assert int(ds.manifest["pack_len"]) == LONG_PACK
    seq = ds.seq_tokens(1)
    assert seq.shape == (LONG_PACK + 1,) and seq.dtype == np.uint16
    x, y = ds[2]
    np.testing.assert_array_equal(x[1:], y[:-1])  # the next-token shift

    from distribuuuu_tpu.data import construct_train_loader

    cfg.DATA.FORMAT = "tokens"
    cfg.LM.SEQ_LEN = LONG_PACK
    cfg.MODEL.NUM_CLASSES = 320
    cfg.TRAIN.DATASET = str(tmp_path)
    cfg.TRAIN.BATCH_SIZE = 1
    loader = construct_train_loader()
    assert loader.can_save_state()
    loader.set_epoch(1)
    full = [b["image"].copy() for b in loader]
    assert full and full[0].shape[1] == LONG_PACK
    sd = loader.state_dict(1)
    assert sd["dataset_identity"]["pack_len"] == LONG_PACK
    fresh = construct_train_loader()
    assert fresh.load_state_dict(sd) == 1
    fresh.set_epoch(1)
    resumed = [b["image"].copy() for b in fresh]
    assert len(resumed) == len(full) - 1
    for a, b in zip(resumed, full[1:]):
        np.testing.assert_array_equal(a, b)


def test_empty_long_pack_refused_at_pack_time(tmp_path):
    """A corpus shorter than one pack_len+1 window refuses at PACK time
    with the arithmetic — not as an empty split the loader trips over
    later. No manifest may be committed."""
    import os

    split = tmp_path / "train"
    short = [np.arange(500, dtype=np.uint16)]  # 501 tokens < 4097
    with pytest.raises(ValueError, match=r"pack_len\+1=4097"):
        token_shards.write_token_shards(
            str(split),
            token_shards.pack_token_stream(short, LONG_PACK),
            LONG_PACK,
        )
    assert not os.path.exists(os.path.join(str(split), "MANIFEST.json"))


def test_midepoch_resume_trajectory_pin(pack_dir):
    """The acceptance pin: training k steps, 'preempting', and resuming
    from the cursor reproduces the uninterrupted run's state EXACTLY
    (same batches in the same order through the same step fn)."""
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.parallel import mesh as mesh_lib
    from distribuuuu_tpu.parallel.partition import lowering, topology
    from distribuuuu_tpu.utils.optim import construct_optimizer

    cfg.MODEL.ARCH = "gpt_nano"
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.LM.SEQ_LEN = PACK
    topo = topology.from_cfg(cfg)
    mesh = mesh_lib.mesh_from_cfg(cfg)
    model = trainer.build_model_from_cfg(topo)
    low = lowering.lower(
        model, construct_optimizer(), topk=5, mesh=mesh, topology=topo,
        im_size=32,
    )

    def run(batches):
        state = low.init_state(jax.random.key(0), 32)
        for hb in batches:
            state, _ = low.train_step(state, low.put_batch(hb))
        return jax.device_get(state.params)

    loader = _token_loader(pack_dir, batch=1)
    loader.set_epoch(1)
    straight = run(list(loader))
    # interrupted at batch 2 + exact resume
    part1 = []
    loader.set_epoch(1)
    for i, hb in enumerate(loader):
        part1.append(hb)
        if i + 1 == 2:
            break
    sd = loader.state_dict(2)
    fresh = _token_loader(pack_dir, batch=1)
    fresh.load_state_dict(sd)
    fresh.set_epoch(1)
    resumed = run(part1 + list(fresh))
    jax.tree.map(np.testing.assert_array_equal, straight, resumed)
