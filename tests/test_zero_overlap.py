"""Latency-hiding ZeRO (ISSUE 15): the gather-once schedule and the
collective/compute overlap knobs as a partition-layer transform.

Three contracts, each pinned toy-sized (tier-1 sits at ~800s of the
870s budget — every compile here is a two-Dense MLP on the 8-device
mesh):

* SCHEDULE — ZeRO-3 FSDP leaves are all-gathered ONCE at step entry
  (specs.gather_schedule over the spec algebra, no per-model code); the
  compiled census shows ~1 gather/leaf and the committed analyzer
  artifact pins the real dp8·zero3[resnet18] drop (195 → ≤25).
* BIT-IDENTITY — ZERO.OVERLAP on ≡ off produces bit-identical params
  (the off arm only inserts optimization_barrier joins; values cannot
  differ by construction), at stage 1 AND stage 3, per-step and
  grad-accum paths.
* PER-SHARD FUSED UPDATE — KERNELS.OPT_UPDATE=pallas under a ZeRO
  layout lowers through shard_map on each rank's 1/N slice
  (opt_update.per_shard_update) and tracks the optax arm jit-vs-jit.
"""

import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import flax.linen as nn
import pytest
from jax.sharding import PartitionSpec as P

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.parallel import mesh as mesh_lib, sharding as sharding_lib, zero
from distribuuuu_tpu.parallel.partition import (
    lowering,
    specs,
    topology as topo_lib,
)
from distribuuuu_tpu.utils.optim import construct_optimizer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

IM = 8  # toy image edge; MLP flattens it — smallest geometry that shards


class ToyMLP(nn.Module):
    """Two Dense layers over flattened pixels: the smallest model whose
    kernels clear zero.MIN_SHARD_ELEMS, so the ZeRO transform genuinely
    shards leaves (kernel0: 192×128 = 24576 elems ≥ 8192)."""

    num_classes: int = 8

    @nn.compact
    def __call__(self, x, train=False):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, name="Body_0")(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes, name="Head_1")(x)


def _lower_toy(stage: int, overlap=True, ahead=-1, accum=1):
    cfg.MODEL.NUM_CLASSES = 8
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.OPTIM.BASE_LR = 0.01
    cfg.MESH.DATA = -1
    cfg.MESH.ZERO = stage
    cfg.ZERO.OVERLAP = overlap
    cfg.ZERO.GATHER_AHEAD = ahead
    topo = topo_lib.from_cfg(cfg)
    mesh = mesh_lib.mesh_from_cfg(cfg)
    model = ToyMLP()
    return mesh, lowering.lower(
        model, construct_optimizer(), 2, mesh=mesh, topology=topo,
        im_size=IM, accum=accum,
    )


def _toy_batch(accum: int = 1):
    rng = np.random.default_rng(0)
    n = 16
    return {
        "image": rng.standard_normal((n, IM, IM, 3)).astype(np.float32),
        "label": rng.integers(0, 8, (n,)).astype(np.int32),
    }


def _run_steps(stage, overlap, ahead=-1, n=3, accum=1):
    mesh, low = _lower_toy(stage, overlap=overlap, ahead=ahead, accum=accum)
    state = low.init_state(jax.random.key(0), IM)
    batch = low.put_batch(_toy_batch())
    for _ in range(n):
        state, m = low.train_step(state, batch)
    return jax.device_get(state.params), float(m["loss"]), low, mesh


def _maxdiff(a, b):
    return max(jax.tree.leaves(jax.tree.map(
        lambda x, y: float(np.abs(np.asarray(x) - np.asarray(y)).max()),
        a, b,
    )))


# ------------------------------------------------------- spec algebra


def test_strip_data_axis_inverts_add_data_axis():
    cases = [
        (P(), (3, 3, 64, 128)),
        (P(None, None, None, "model"), (3, 3, 64, 128)),
        (P(None, None, None, "model"), (7, 7, 3, 64)),
    ]
    for base, shape in cases:
        added = zero.add_data_axis(base, shape, 8, {"model": 1})
        stripped = zero.strip_data_axis(added)
        # canonical equality: trailing Nones are cosmetic
        assert specs.canonicalize(stripped, {}) == specs.canonicalize(
            base, {}
        ), (base, added, stripped)
    # a spec data never touched is returned unchanged
    assert zero.strip_data_axis(P("model", None)) == P("model", None)


def test_gather_schedule_derivation_and_refusal():
    mesh, low = _lower_toy(3)
    layout = low.layout
    needs = [
        "data" in specs.spec_axes(sh.spec)
        for sh in jax.tree.leaves(layout["params"])
    ]
    assert sum(needs) >= 1  # the toy genuinely shards
    # -1: every qualifying leaf hoisted
    full = jax.tree.leaves(specs.gather_schedule(layout, -1))
    assert full == needs
    # 0: the legacy per-use schedule — nothing hoisted
    assert not any(jax.tree.leaves(specs.gather_schedule(layout, 0)))
    # 1: only group-0 leaves (Body_0) hoisted, Head_1 (group 1) not
    one = specs.gather_schedule(layout, 1)
    flat = jax.tree_util.tree_flatten_with_path(one)[0]
    for path, hoisted in flat:
        p = specs.leaf_path(path)
        if "Head_1/kernel" in p:
            assert not hoisted, p
    assert sum(jax.tree.leaves(one)) >= 1
    with pytest.raises(ValueError, match="GATHER_AHEAD"):
        specs.gather_schedule(layout, -2)
    # stage 1: params rest replicated — empty schedule at any depth
    _, low1 = _lower_toy(1)
    assert not any(jax.tree.leaves(specs.gather_schedule(low1.layout, -1)))


def test_compute_layout_strips_only_data():
    _, low = _lower_toy(3)
    gathered = specs.compute_layout(low.layout)
    for sh in jax.tree.leaves(gathered):
        assert "data" not in specs.spec_axes(sh.spec)


# ------------------------------------------------ schedule in the HLO


def test_gather_once_census_on_toy_program():
    """The compiled ZeRO-3 step all-gathers each FSDP leaf once (the
    gather-once schedule), stays within the spec-algebra bound, and the
    collectives lint raises no finding; the per-use escape hatch
    (GATHER_AHEAD=0) still compiles and keeps the same loss math."""
    from distribuuuu_tpu.analysis import hlo
    from distribuuuu_tpu.analysis.passes import collectives

    mesh, low = _lower_toy(3)
    state_sds, batch_sds = low.abstract_args()
    lowered = low.train_step.lower(state_sds, batch_sds)
    compiled = lowered.compile()
    census = hlo.collective_census(compiled.as_text(), mesh)
    exp = specs.collective_expectations(low.layout, low.topology,
                                        gather_ahead=-1)
    data_gathers = [
        op for op in census
        if op["kind"] == "all-gather" and op["axes"] == ("data",)
    ]
    assert exp["zero_sharded"] >= 1
    assert len(data_gathers) <= exp["gather_bound"], (
        len(data_gathers), exp,
    )
    # the entry gather carries the attribution scope: axis-qualified in
    # the LOWERED StableHLO locs (compiled HLO metadata strips the
    # ``@axes`` suffix on this jax line — same caveat as the PP scopes,
    # PR 8), and attributed by the census from the compiled metadata
    assert "zero_gather_once@data" in hlo.stablehlo_with_locs(lowered)
    assert any(
        "zero_gather_once" in op["scope"] for op in data_gathers
    ), [op["scope"] for op in data_gathers]


def test_committed_census_artifact_pins_the_drop():
    """ANALYSIS_r01.json (the regenerated referee): dp8·zero3[resnet18]
    all-gather census ≤ 25 (~1/leaf; the PR 14 baseline priced the
    per-use schedule at 195 ≈ 9.3/leaf) and the ZeRO-3 gather-storm
    waivers are GONE from the baseline."""
    with open(os.path.join(REPO, "ANALYSIS_r01.json")) as f:
        doc = json.load(f)
    case = next(
        c for c in doc["cases"] if c["name"] == "sweep/dp8·zero3[resnet18]"
    )
    ag = case["collective_ledger"]["data"]["all-gather"]["count"]
    assert ag <= 25, ag
    pp = next(
        c for c in doc["cases"]
        if c["name"] == "sweep/dp2·pp4·zero3[vit_tiny]"
    )
    assert pp["collective_ledger"]["data"]["all-gather"]["count"] <= 20
    with open(os.path.join(REPO, "ANALYSIS_BASELINE.json")) as f:
        base = json.load(f)
    keys = [w["key"] for w in base["waivers"]]
    assert not any("gather-storm" in k for k in keys), keys


# ------------------------------------------------------- bit-identity


@pytest.mark.parametrize("stage", [1, 3])
def test_overlap_on_off_bit_identical(stage):
    """ZERO.OVERLAP off only inserts optimization_barrier joins — the
    synchronous control arm of the A/B is bit-identical, both ZeRO
    stages, 3 steps (the ISSUE 15 acceptance pin)."""
    on, loss_on, _, _ = _run_steps(stage, overlap=True)
    off, loss_off, _, _ = _run_steps(stage, overlap=False)
    assert _maxdiff(on, off) == 0.0
    assert loss_on == loss_off


def test_overlap_bit_identical_on_accum_path():
    """Same pin through the grad-accumulation scan (gather-once hoists
    OUTSIDE the microbatch scan — one gather per optimizer step)."""
    on, _, _, _ = _run_steps(3, overlap=True, n=2, accum=2)
    off, _, _, _ = _run_steps(3, overlap=False, n=2, accum=2)
    assert _maxdiff(on, off) == 0.0


def test_partial_hoist_values_unchanged():
    """GATHER_AHEAD is pure scheduling: hoisting only the first group
    produces the same values as hoisting everything (constraints move,
    math does not)."""
    full, _, _, _ = _run_steps(3, overlap=True, ahead=-1, n=2)
    part, _, _, _ = _run_steps(3, overlap=True, ahead=1, n=2)
    assert _maxdiff(full, part) == 0.0


def test_eval_step_gathers_once_at_zero3():
    """lower() threads the schedule into the eval step: it runs on the
    sharded rest state and its program carries the gather-once scope."""
    mesh, low = _lower_toy(3)
    state = low.init_state(jax.random.key(0), IM)
    hb = _toy_batch()
    hb["mask"] = np.ones((16,), np.float32)
    batch = sharding_lib.shard_batch(mesh, hb)
    m = low.eval_step(state, batch)
    assert float(m["count"]) == 16.0
    from distribuuuu_tpu.analysis import hlo

    txt = hlo.stablehlo_with_locs(low.eval_step.lower(state, batch))
    assert "zero_gather_once@data" in txt


# ------------------------------------------- per-shard fused update


@pytest.mark.parametrize("stage", [1, 3])
def test_per_shard_fused_update_matches_optax(stage):
    """KERNELS.OPT_UPDATE=pallas under a real ZeRO lowering: the
    shard_map per-shard kernel (no whole-leaf gather — the r14
    replicated-pin is deleted) tracks the optax arm jit-vs-jit within
    the kernel tier's pinned tolerance."""
    ref, _, _, _ = _run_steps(stage, overlap=True, n=2)
    config.reset_cfg()
    cfg.KERNELS.OPT_UPDATE = "pallas"
    fused, _, low, mesh = _run_steps(stage, overlap=True, n=2)
    assert _maxdiff(ref, fused) <= 5e-6
    # and the fused program does NOT reintroduce the whole-leaf gathers:
    # census stays within the same gather-once bound as the optax arm
    from distribuuuu_tpu.analysis import hlo

    state_sds, batch_sds = low.abstract_args()
    compiled = low.train_step.lower(state_sds, batch_sds).compile()
    census = hlo.collective_census(compiled.as_text(), mesh)
    exp = specs.collective_expectations(low.layout, low.topology,
                                        gather_ahead=-1)
    ag = sum(
        1 for op in census
        if op["kind"] == "all-gather" and op["axes"] == ("data",)
    )
    assert ag <= exp["gather_bound"], (ag, exp)


# ------------------------------------------------- telemetry + bench


def test_zero_schedule_telemetry_declared_and_deduped(monkeypatch):
    from distribuuuu_tpu.telemetry import schema

    assert "zero.schedule" in schema.KINDS
    _, low = _lower_toy(3)
    records = []
    monkeypatch.setattr(
        "distribuuuu_tpu.utils.jsonlog.metrics_log",
        lambda kind, **f: records.append((kind, f)),
    )
    lowering._logged_schedules.clear()
    lowering._log_zero_schedule(low.layout, low.topology)
    lowering._log_zero_schedule(low.layout, low.topology)  # deduped
    assert len(records) == 1
    kind, fields = records[0]
    assert kind == "zero.schedule"
    assert schema.KINDS["zero.schedule"] <= set(fields)
    assert fields["stage"] == 3 and fields["hoisted"] >= 1


def test_bench_index_zero_overlap_series(tmp_path):
    """bench_history indexes the BENCH_r10 zero_overlap section as
    zero_overlap_* series (outside the img/s gate patterns), and the
    committed BENCH_INDEX.json carries them."""
    import sys

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import bench_history
    finally:
        sys.path.remove(os.path.join(REPO, "tools"))
    doc = {
        "zero_overlap": {
            "cases": {
                "dp8_zero3": {"arms": {
                    "overlap_on": {"data_all_gathers": 22, "step_ms": 10.0},
                    "per_use": {"data_all_gathers": 196, "step_ms": 12.0},
                }},
            },
        },
    }
    with open(tmp_path / "BENCH_r10.json", "w") as f:
        json.dump(doc, f)
    idx = bench_history.build_index(str(tmp_path))
    s = idx["series"]
    assert s["zero_overlap_dp8_zero3_overlap_on_data_gathers"][0]["value"] == 22
    assert s["zero_overlap_dp8_zero3_per_use_data_gathers"][0]["value"] == 196
    assert not any("images_per_sec" in k for k in s)
    # committed artifacts: BENCH_r10.json indexed into BENCH_INDEX.json,
    # and the gather-once arm beats per-use on the census
    with open(os.path.join(REPO, "BENCH_INDEX.json")) as f:
        committed = json.load(f)
    on = committed["series"]["zero_overlap_dp8_zero3_overlap_on_data_gathers"]
    per_use = committed["series"]["zero_overlap_dp8_zero3_per_use_data_gathers"]
    assert on[-1]["value"] < per_use[-1]["value"]


# ------------------------------------------------- trace overlap rollup


def test_trace_overlap_fraction_rollup():
    import sys

    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import trace_report
    finally:
        sys.path.remove(os.path.join(REPO, "tools"))

    def ev(line, op, start, dur, name="fusion.1"):
        return {"line": line, "name": name, "op_name": op,
                "bytes": 0, "start_ns": start, "dur_ns": dur}

    gather = "jit(train_step)/zero_gather_once@data/all-gather"
    compute = "jit(train_step)/jvp(fwd)/conv"
    # collective on line A [0, 100); compute on line B [50, 150):
    # 50 of 100 collective ns hidden -> fraction 0.5
    events = [
        ev("lineA", gather, 0.0, 100.0, name="all-gather.1"),
        ev("lineB", compute, 50.0, 100.0),
    ]
    ov = trace_report.overlap_fraction(events)
    assert ov["fraction"] == 0.5
    assert ov["zero_collective_ms"] == pytest.approx(1e-4)
    # fully serialized: fraction 0
    serial = [
        ev("lineA", gather, 0.0, 100.0, name="all-gather.1"),
        ev("lineA", compute, 100.0, 100.0),
    ]
    assert trace_report.overlap_fraction(serial)["fraction"] == 0.0
    # fully hidden: fraction 1
    hidden = [
        ev("lineA", gather, 10.0, 50.0, name="all-gather.1"),
        ev("lineB", compute, 0.0, 100.0),
    ]
    assert trace_report.overlap_fraction(hidden)["fraction"] == 1.0
    # no start stamps (older fixtures) -> no section, summary still works
    legacy = [{"line": "lineA", "name": "fusion.1", "op_name": compute,
               "bytes": 0, "dur_ns": 5.0}]
    assert trace_report.overlap_fraction(legacy) is None
    summary = trace_report.summarize_events(events, steps=1)
    assert summary["overlap"]["fraction"] == 0.5
    assert "busy_ms_per_step" in summary
