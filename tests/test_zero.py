"""ZeRO / FSDP sharding over the data axis (MESH.ZERO, parallel/zero.py).

The reference replicates params + optimizer state per rank (torch DDP,
ref: /root/reference/distribuuuu/trainer.py:134, utils.py:187-196). The
ZeRO stages must (a) actually deduplicate the state across the 8-device
CPU mesh — asserted on the placed shard sizes, not just on specs — and
(b) leave the math unchanged: the same stream trained at stage 0/1/3
produces the same trajectory modulo float reduction order.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import PartitionSpec as P

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu import trainer
from distribuuuu_tpu.parallel import mesh as mesh_lib, sharding as sharding_lib
from distribuuuu_tpu.parallel import zero
from distribuuuu_tpu.utils.optim import construct_optimizer

BATCH = 16
N_STEPS = 3


def stream_batch(step: int, n: int = BATCH):
    rng = np.random.default_rng(7_000 + step)
    images = rng.standard_normal((n, 32, 32, 3)).astype(np.float32)
    labels = (
        (images.mean(axis=(1, 2, 3)) * 40.0).astype(np.int64) % 10
    ).astype(np.int32)
    images += labels[:, None, None, None] * 0.1
    return {"image": images, "label": labels, "mask": np.ones((n,), np.float32)}


def _setup(stage: int, model_axis: int = 1, optimizer_kind: str = "sgd"):
    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.BN_GROUP = 8
    cfg.OPTIM.BASE_LR = 0.05
    cfg.OPTIM.OPTIMIZER = optimizer_kind
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.MESH.DATA = -1
    cfg.MESH.MODEL = model_axis
    cfg.MESH.ZERO = stage
    trainer.check_trainer_mesh()
    mesh = mesh_lib.mesh_from_cfg(cfg)
    model = trainer.build_model_from_cfg()
    layout = trainer._state_layout(model, mesh, 32) if stage else None
    state = trainer.create_train_state(
        model, jax.random.key(0), mesh, 32, layout=layout
    )
    step = trainer.make_train_step(
        model, construct_optimizer(), topk=5, layout=layout
    )
    return mesh, model, state, step


def _momentum_leaves(opt_state):
    """All param-shaped momentum/trace arrays inside the optax state."""
    return [
        x
        for x in jax.tree.leaves(opt_state)
        if hasattr(x, "ndim") and x.ndim >= 2
    ]


def _run(stage: int, model_axis: int = 1):
    mesh, model, state, step = _setup(stage, model_axis)
    losses = []
    for it in range(N_STEPS):
        batch = sharding_lib.shard_batch(mesh, stream_batch(it))
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    return state, losses


# ---------------------------------------------------------------- unit level


def test_add_data_axis_picks_largest_free_divisible_dim():
    # conv kernel [3, 3, 64, 128], data=8: out-dim (largest, divisible)
    assert zero.add_data_axis(P(), (3, 3, 64, 128), 8) == P(
        None, None, None, "data"
    )
    # TP-taken out dim at real TP (model=2): remaining extents tie at 64,
    # the free in-dim wins
    assert zero.add_data_axis(
        P(None, None, None, "model"), (3, 3, 64, 128), 8, {"model": 2}
    ) == P(None, None, "data", "model")
    # TP annotation with a collapsed model axis (size 1): data appends to
    # the annotated out dim — largest remaining extent
    assert zero.add_data_axis(
        P(None, None, None, "model"), (3, 3, 64, 128), 8, {"model": 1}
    ) == P(None, None, None, ("model", "data"))
    # stem-shaped kernel (7,7,3,64): only the annotated out dim divides
    assert zero.add_data_axis(
        P(None, None, None, "model"), (7, 7, 3, 64), 8, {"model": 1}
    ) == P(None, None, None, ("model", "data"))
    # idempotent: an already-ZeRO'd spec is left alone
    assert zero.add_data_axis(
        P(None, None, None, ("model", "data")), (3, 3, 64, 128), 8
    ) == P(None, None, None, ("model", "data"))
    # nothing divisible: unchanged
    assert zero.add_data_axis(P(), (3, 3, 63, 127), 8) == P()
    # too small to be worth sharding: unchanged
    assert zero.add_data_axis(P(), (64,), 8) == P()
    # data axis of 1 (single chip): unchanged
    assert zero.add_data_axis(P(), (3, 3, 64, 128), 1) == P()


def test_zero_step_without_layout_refused():
    """ADVICE r4 (medium): the docstring's promise is now enforced — a
    step built without the ZeRO layout while MESH.ZERO is set raises
    instead of silently producing a neither-DDP-nor-ZeRO layout."""
    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MESH.ZERO = 1
    model = trainer.build_model_from_cfg()
    with pytest.raises(ValueError, match="ZeRO state layout"):
        trainer.make_train_step(model, construct_optimizer(), topk=5)
    with pytest.raises(ValueError, match="ZeRO state layout"):
        trainer.make_scan_train_step(
            model, construct_optimizer(), topk=5, fold=2
        )
    config.reset_cfg()


def test_zero_stage_validation():
    config.reset_cfg()
    cfg.MESH.ZERO = 2
    with pytest.raises(ValueError, match="stage 2 is"):
        trainer.check_trainer_mesh()
    # ZeRO-3 under PP was refused before the partition layer (r11); it is
    # now a supported LAYOUT — FSDP params gather at the stage shard_map
    # boundary (in_specs), the backward reduce-scatters. The stanza must
    # validate and classify with both features.
    config.reset_cfg()
    cfg.MESH.ZERO = 3
    cfg.MESH.PIPE = 2
    cfg.MODEL.ARCH = "vit_tiny"
    topo = trainer.check_trainer_mesh()
    assert {"pp", "zero3"} <= set(topo.describe()["features"])


# ------------------------------------------------------------- layout level


def test_zero1_shards_optimizer_state_not_params():
    _, _, state, _ = _setup(stage=1)
    n_dev = jax.device_count()
    sharded = 0
    for leaf in _momentum_leaves(state.opt_state):
        if leaf.size >= zero.MIN_SHARD_ELEMS:
            shard = leaf.addressable_shards[0].data
            assert shard.size == leaf.size // n_dev, leaf.shape
            sharded += 1
    assert sharded >= 10  # every conv kernel's momentum buffer
    # params stay replicated (DDP rest layout)
    for leaf in jax.tree.leaves(state.params):
        assert leaf.addressable_shards[0].data.size == leaf.size


@pytest.mark.slow
def test_zero3_shards_params_too():
    _, _, state, _ = _setup(stage=3)
    n_dev = jax.device_count()
    sharded = 0
    for leaf in jax.tree.leaves(state.params):
        if leaf.addressable_shards[0].data.size == leaf.size // n_dev:
            sharded += 1
    assert sharded >= 10
    # batch_stats stay replicated (updated from in-graph psums every step)
    for leaf in jax.tree.leaves(state.batch_stats):
        assert leaf.addressable_shards[0].data.size == leaf.size


@pytest.mark.slow
def test_zero1_adamw_shards_both_moments():
    _, _, state, _ = _setup(stage=1, optimizer_kind="adamw")
    n_dev = jax.device_count()
    big = [
        leaf
        for leaf in _momentum_leaves(state.opt_state)
        if leaf.size >= zero.MIN_SHARD_ELEMS
    ]
    # adamw carries mu AND nu per param: both must be deduplicated
    assert len(big) >= 20
    for leaf in big:
        assert leaf.addressable_shards[0].data.size == leaf.size // n_dev


@pytest.mark.slow
def test_zero_composes_with_tp():
    mesh, _, state, _ = _setup(stage=1, model_axis=2)
    found_both = 0
    for leaf in _momentum_leaves(state.opt_state):
        spec = leaf.sharding.spec
        names = {n for e in spec if e for n in ((e,) if isinstance(e, str) else e)}
        if {"data", "model"} <= names:
            found_both += 1
    # TP-sharded kernels get ZeRO on a different dim: sharded over BOTH axes
    assert found_both >= 5, found_both


# ---------------------------------------------------------- trajectory level


@pytest.mark.slow
def test_zero_trajectories_match_ddp_layout():
    """Stages 0/1/3 run the same math — layout only. Step-0 loss is
    pre-update (identical init), later steps bound by reduction-order
    drift; all must stay in the same convergence family."""
    _, base = _run(stage=0)
    for stage in (1, 3):
        _, traj = _run(stage=stage)
        assert np.isfinite(traj).all(), (stage, traj)
        np.testing.assert_allclose(
            traj[0], base[0], rtol=0, atol=1e-5, err_msg=f"stage {stage}"
        )
        np.testing.assert_allclose(
            traj[1], base[1], rtol=0, atol=2e-2, err_msg=f"stage {stage}"
        )
        assert abs(traj[2] - base[2]) < 0.5, (stage, traj[2], base[2])


@pytest.mark.slow
def test_zero3_eval_step_works_on_sharded_params():
    mesh, model, state, _ = _setup(stage=3)
    eval_step = trainer.make_eval_step(model, topk=5)
    batch = sharding_lib.shard_batch(mesh, stream_batch(0))
    m = eval_step(state, batch)
    assert float(m["count"]) == BATCH
    assert np.isfinite(float(m["loss_sum"]))


@pytest.mark.slow
def test_zero_checkpoint_roundtrip(tmp_path):
    """Save at stage 1, restore through the template-driven placement
    (trainer._place_like): values equal, rest layout preserved."""
    from distribuuuu_tpu.utils import checkpoint as ckpt

    _, _, state, step = _setup(stage=1)
    mesh = mesh_lib.mesh_from_cfg(cfg)
    batch = sharding_lib.shard_batch(mesh, stream_batch(0))
    state, _ = step(state, batch)
    cfg.defrost()
    cfg.OUT_DIR = str(tmp_path)
    cfg.freeze()
    ckpt.save_checkpoint(trainer._state_tree(state), 0, 0.0, False)
    cfg.defrost()

    restored = ckpt.load_checkpoint(ckpt.get_last_checkpoint())
    placed = trainer._place_like(
        state.opt_state,
        ckpt.unpack_opt_state(state.opt_state, restored["opt_state"]),
    )
    for a, b in zip(
        _momentum_leaves(state.opt_state), _momentum_leaves(placed)
    ):
        assert a.sharding == b.sharding
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_zero1_composes_with_pp():
    """ZeRO-1 × MESH.PIPE>1 (ADVICE r4): the pipelined param tree — stacked
    per-stage leaves entering the pipe shard_map — is a materially
    different layout than the data-axis-only cases above. Asserts (a) the
    momentum buffers are genuinely deduplicated over data ON TOP of the
    pipe stacking (shard-size accounting) and (b) the trajectory matches
    the stage-0 PP run."""

    def run(stage):
        config.reset_cfg()
        cfg.MODEL.ARCH = "vit_tiny"
        cfg.MODEL.NUM_CLASSES = 10
        cfg.TRAIN.IM_SIZE = 32
        cfg.DEVICE.COMPUTE_DTYPE = "float32"
        cfg.MESH.PIPE = 4
        cfg.MESH.MICROBATCH = 4
        cfg.MESH.DATA = -1
        cfg.MESH.ZERO = stage
        trainer.check_trainer_mesh()
        mesh = mesh_lib.mesh_from_cfg(cfg)
        model = trainer.build_model_from_cfg()
        layout = trainer._state_layout(model, mesh, 32) if stage else None
        state = trainer.create_train_state(
            model, jax.random.key(0), mesh, 32, layout=layout
        )
        step = trainer.make_train_step(
            model, construct_optimizer(), topk=5, layout=layout
        )
        losses = []
        for it in range(N_STEPS):
            batch = sharding_lib.shard_batch(mesh, stream_batch(it))
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return mesh, state, losses

    mesh, state, traj = run(stage=1)
    n_dev = jax.device_count()
    pipe = dict(mesh.shape)["pipe"]
    assert pipe == 4 and dict(mesh.shape)["data"] == n_dev // 4

    both = 0
    for leaf in _momentum_leaves(state.opt_state):
        if leaf.size // pipe < zero.MIN_SHARD_ELEMS:
            continue
        spec = leaf.sharding.spec
        names = {
            n
            for e in spec
            if e
            for n in ((e,) if isinstance(e, str) else e)
        }
        if {"data", "pipe"} <= names:
            shard = leaf.addressable_shards[0].data
            assert shard.size == leaf.size // n_dev, (leaf.shape, spec)
            both += 1
    # every stacked transformer-block kernel's momentum must carry both
    assert both >= 8, both

    _, _, base = run(stage=0)
    assert np.isfinite(traj).all(), traj
    np.testing.assert_allclose(traj[0], base[0], rtol=0, atol=1e-5)
    np.testing.assert_allclose(traj[1], base[1], rtol=0, atol=2e-2)
    assert abs(traj[2] - base[2]) < 0.5, (traj, base)
