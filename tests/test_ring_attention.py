"""Ring / Ulysses sequence-parallel attention vs the single-device oracle.

Runs on the virtual 8-device CPU mesh (conftest.py) — the JAX analogue of the
reference's "multi-node without a cluster" trick (ref: README.md:119-144).
The reference itself has no sequence parallelism (SURVEY.md §5.7); these ops
are the TPU framework's long-context capability, so they are tested for exact
numerics (forward AND gradients) against full attention.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distribuuuu_tpu.ops import ring_attention as ra
from distribuuuu_tpu.parallel import mesh as mesh_lib


def _qkv(b=2, h=4, s=32, d=16, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.standard_normal((b, h, s, d)).astype(np.float32))
        for _ in range(3)
    )


@pytest.fixture(scope="module")
def mesh():
    # data=2 × seq=4 — both batch and sequence sharded
    return mesh_lib.build_mesh(data=2, model=1, seq=4)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(mesh, causal):
    q, k, v = _qkv()
    want = ra.reference_attention(q, k, v, causal=causal)
    got = ra.ring_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(mesh, causal):
    q, k, v = _qkv(seed=1)
    want = ra.reference_attention(q, k, v, causal=causal)
    got = ra.ulysses_attention(q, k, v, mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow  # dominates the fast tier; full tier covers it
def test_ring_gradients_match(mesh):
    q, k, v = _qkv(s=16, seed=2)

    def loss_ref(q, k, v):
        return (ra.reference_attention(q, k, v, causal=True) ** 2).sum()

    def loss_ring(q, k, v):
        return (ra.ring_attention(q, k, v, mesh, causal=True) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_ring_under_jit_seq_only(mesh):
    # seq-only sharding (data axis unused) and jit around the shard_map
    q, k, v = _qkv(b=1, seed=3)
    fn = jax.jit(
        lambda q, k, v: ra.ring_attention(q, k, v, mesh, data_axis=None,
                                          causal=True)
    )
    want = ra.reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(fn(q, k, v)), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_ulysses_rejects_indivisible_heads(mesh):
    q, k, v = _qkv(h=2)  # 2 heads, seq axis 4
    with pytest.raises(Exception):
        ra.ulysses_attention(q, k, v, mesh)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_impl_matches_reference(mesh, causal):
    """r4 (VERDICT r3 #4): ring block updates through the Pallas flash
    kernel (interpreter on this CPU mesh) — the (o, lse) state merge plus
    the causal cond-skip of fully-masked source blocks must reproduce the
    exact reference, like the einsum path does."""
    q, k, v = _qkv(s=32, d=16, seed=3)
    want = ra.reference_attention(q, k, v, causal=causal)
    got = ra.ring_attention(q, k, v, mesh, causal=causal, impl="flash")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.slow  # pallas-interpreter backward on an 8-device CPU mesh
def test_ring_flash_gradients_match(mesh):
    """Gradients through the flash-state ring: custom-vjp blocks, the lse
    cotangent (corr_b depends on lse_b), and the cond-skip all compose."""
    q, k, v = _qkv(s=16, seed=4)

    def loss_ref(q, k, v):
        return (ra.reference_attention(q, k, v, causal=True) ** 2).sum()

    def loss_ring(q, k, v):
        return (
            ra.ring_attention(q, k, v, mesh, causal=True, impl="flash") ** 2
        ).sum()

    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4, err_msg=name
        )
