"""Input-pipeline overlap engine (r6 tentpole): timeline record schema,
device-prefetch-ring determinism (bit-identical results at depth 0/1/2),
validate()-overlap equivalence, and the overlap_report attribution math.
"""

import json
import os
import subprocess
import sys

import jax
import numpy as np
import pytest

import distribuuuu_tpu.config as config
from distribuuuu_tpu.config import cfg
from distribuuuu_tpu.data.dummy import DummyDataset
from distribuuuu_tpu.data.loader import Loader, device_prefetch
from distribuuuu_tpu.utils import jsonlog

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------- generator unit level
def test_device_prefetch_preserves_order_and_values():
    """Every depth yields the same batches in the same order with the
    same values — the ring only moves WHEN transfers are dispatched."""
    sums = {}
    for depth in (0, 1, 3):
        ds = DummyDataset(length=16, size=8)
        loader = Loader(ds, batch_size=4, shuffle=True, drop_last=True,
                        workers=2)
        loader.set_epoch(0)
        out = list(device_prefetch(loader, lambda hb: hb, depth))
        assert [it for it, _, _ in out] == list(range(4))
        sums[depth] = [float(np.sum(b["image"])) for _, b, _ in out]
        for _, _, tl in out:
            # loader-side + consumer-side stamps, in stage order
            assert tl["submit"] <= tl["dec0"] <= tl["dec1"] <= tl["asm1"]
            assert tl["get0"] <= tl["get1"] <= tl["put0"] <= tl["put1"]
            assert tl["n"] == 4
    assert sums[0] == sums[1] == sums[3]


def test_device_prefetch_ring_dispatches_ahead():
    """With depth d the put of batch k+d is dispatched BEFORE batch k is
    consumed (that is the overlap); with depth 0 it is not."""
    for depth, expect_ahead in ((0, False), (2, True)):
        ds = DummyDataset(length=24, size=8)
        loader = Loader(ds, batch_size=4, shuffle=False, drop_last=True,
                        workers=1)
        loader.set_epoch(0)
        put_order = []
        gen = device_prefetch(
            loader, lambda hb: put_order.append(len(put_order)) or hb, depth
        )
        next(gen)  # consumer holds batch 0
        assert (len(put_order) > 1) == expect_ahead
        gen.close()


# ----------------------------------------------------- timeline record schema
def test_timeline_log_schema(tmp_path):
    jsonlog.setup_metrics_log(str(tmp_path))
    jsonlog.timeline_log(
        "train", epoch=3, batch=7, n=64,
        submit=1.0, dec0=1.1, dec1=1.5, asm1=1.6, get0=0.9, get1=1.7,
        put0=1.7, put1=1.8, step0=1.9, step1=2.5,
        bogus=123.0,  # not a stage field: must be dropped, not logged
    )
    jsonlog.close_metrics_log()
    (rec,) = [
        json.loads(ln)
        for ln in open(tmp_path / "metrics.jsonl").read().splitlines()
    ]
    assert rec["kind"] == "timeline" and rec["v"] == jsonlog.TIMELINE_SCHEMA
    assert rec["phase"] == "train" and rec["epoch"] == 3
    assert rec["batch"] == 7 and rec["n"] == 64
    for k in jsonlog.TIMELINE_STAGES:
        assert k in rec
    assert "bogus" not in rec
    assert rec["t"] > 1e9  # wall-clock record stamp rides along


# ------------------------------------------------------- attribution math
def _rec(batch, get0, get1, put0, put1, step0, step1, dec0, dec1, asm1,
         n=4, epoch=1, phase="train"):
    return dict(batch=batch, get0=get0, get1=get1, put0=put0, put1=put1,
                step0=step0, step1=step1, dec0=dec0, dec1=dec1, asm1=asm1,
                n=n, epoch=epoch, phase=phase)


def test_attribute_partitions_wall_exactly():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from overlap_report import attribute

    recs = [
        _rec(0, 0.0, 1.0, 1.0, 1.5, 1.5, 3.0, 0.2, 0.8, 0.9),
        _rec(1, 3.0, 3.5, 3.5, 4.0, 4.0, 6.0, 0.5, 2.0, 2.5),
        _rec(0, 0.0, 9.0, 9.0, 9.5, 9.5, 10.0, 0.0, 8.0, 9.0, epoch=2,
             phase="eval"),  # other phase: ignored
    ]
    att = attribute(recs, phase="train")
    assert att["epoch"] == 1 and att["n_batches"] == 2 and att["images"] == 8
    assert att["wall_s"] == 6.0
    assert att["data_wait_s"] == 1.5
    assert att["h2d_s"] == 1.0
    assert att["step_s"] == 3.5
    assert att["other_s"] == 0.0  # the partition is exact
    assert att["attribution_residual_frac"] == 0.0
    assert att["decode_s"] == pytest.approx(2.1)
    assert att["assemble_s"] == pytest.approx(0.6)
    # decode intervals [0.2,0.9] ∪ [0.5,2.5] = [0.2,2.5] → 2.3
    assert att["decode_busy_s"] == pytest.approx(2.3)
    assert att["overlap_efficiency"] == pytest.approx(2.3 / 6.0, abs=1e-4)
    assert att["data_wait_frac"] == pytest.approx(0.25)

    with pytest.raises(ValueError, match="no timeline records"):
        attribute(recs, phase="train", epoch=9)


def test_overlap_report_cli(tmp_path):
    path = tmp_path / "metrics.jsonl"
    recs = [
        {"kind": "train", "epoch": 1},  # non-timeline records are skipped
        {"kind": "timeline",
         **_rec(0, 0.0, 1.0, 1.0, 1.5, 1.5, 3.0, 0.2, 0.8, 0.9)},
        {"kind": "timeline",
         **_rec(1, 3.0, 3.5, 3.5, 4.0, 4.0, 6.0, 0.5, 2.0, 2.5)},
    ]
    path.write_text("".join(json.dumps(r) + "\n" for r in recs))
    out = subprocess.run(
        [sys.executable, "tools/overlap_report.py", "--metrics", str(path)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    last = json.loads(out.stdout.strip().splitlines()[-1])
    assert last["metric"] == "overlap_report"
    assert last["wall_s"] == 6.0 and last["attribution_residual_frac"] == 0.0


# --------------------------------------------------- trainer-level, real steps
def _tiny_train_setup():
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.parallel import mesh as mesh_lib
    from distribuuuu_tpu.utils.optim import construct_optimizer

    config.reset_cfg()
    cfg.MODEL.ARCH = "resnet18"
    cfg.MODEL.NUM_CLASSES = 10
    cfg.MODEL.DUMMY_INPUT = True
    cfg.DEVICE.COMPUTE_DTYPE = "float32"
    cfg.TRAIN.IM_SIZE = 16
    cfg.TRAIN.BATCH_SIZE = 1  # ×8 local devices = per-host batch 8
    cfg.RNG_SEED = 1
    mesh = mesh_lib.build_mesh()
    model = trainer.build_model_from_cfg()
    optimizer = construct_optimizer()
    step = trainer.make_train_step(model, optimizer, topk=5)
    eval_step = trainer.make_eval_step(model, topk=5)
    return trainer, mesh, model, step, eval_step


@pytest.mark.slow  # 44s: trains at every prefetch depth; tier-1 budget
def test_prefetch_ring_bit_identical_and_timeline(tmp_path):
    """Acceptance gate: train_epoch results are BIT-identical at every
    ring depth (0 = unoverlapped, 1, 2), and the per-batch path leaves one
    well-formed timeline record per train batch."""
    from distribuuuu_tpu.utils.logger import get_logger

    trainer, mesh, model, step, _ = _tiny_train_setup()
    finals = {}
    for depth in (0, 1, 2):
        cfg.TRAIN.PREFETCH_DEVICE = depth
        sink_dir = tmp_path / f"d{depth}"
        jsonlog.setup_metrics_log(str(sink_dir))
        state = trainer.create_train_state(
            model, jax.random.key(0), mesh, cfg.TRAIN.IM_SIZE
        )
        loader = Loader(
            DummyDataset(length=24, size=16), batch_size=8, shuffle=True,
            drop_last=True, workers=2,
        )
        state, interrupted, _ = trainer.train_epoch(
            loader=loader, mesh=mesh, state=state, train_step=step,
            epoch=0, logger=get_logger(),
        )
        jsonlog.close_metrics_log()
        assert not interrupted
        finals[depth] = jax.tree.map(np.asarray, jax.device_get(state.params))
        recs = [
            json.loads(ln)
            for ln in open(sink_dir / "metrics.jsonl").read().splitlines()
        ]
        tl = [r for r in recs if r["kind"] == "timeline"]
        assert len(tl) == 3 and [r["batch"] for r in tl] == [0, 1, 2]
        for r in tl:
            assert r["phase"] == "train" and r["n"] == 8
            assert (r["submit"] <= r["dec0"] <= r["dec1"] <= r["asm1"]
                    and r["get0"] <= r["get1"] <= r["put0"] <= r["put1"]
                    <= r["step0"] <= r["step1"])
    for depth in (1, 2):
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(a, b),
            finals[0], finals[depth],
        )


def test_validate_overlap_equivalence(tmp_path):
    """validate() rides the same ring: results identical at depth 0 vs 2,
    including the masked ragged tail, and eval timeline records land."""
    from distribuuuu_tpu.utils.logger import get_logger

    trainer, mesh, model, _, eval_step = _tiny_train_setup()
    state = trainer.create_train_state(
        model, jax.random.key(0), mesh, cfg.TRAIN.IM_SIZE
    )
    results = {}
    for depth in (0, 2):
        cfg.TRAIN.PREFETCH_DEVICE = depth
        sink_dir = tmp_path / f"ev{depth}"
        jsonlog.setup_metrics_log(str(sink_dir))
        loader = Loader(
            DummyDataset(length=20, size=16), batch_size=8, shuffle=False,
            drop_last=False, workers=2,
        )  # 20 → 2 full batches + ragged 4/8 tail
        loader.set_epoch(0)
        results[depth] = trainer.validate(
            loader, mesh, state, eval_step, epoch=0, logger=get_logger()
        )
        jsonlog.close_metrics_log()
        recs = [
            json.loads(ln)
            for ln in open(sink_dir / "metrics.jsonl").read().splitlines()
        ]
        tl = [r for r in recs if r["kind"] == "timeline"]
        assert [r["batch"] for r in tl if r["phase"] == "eval"] == [0, 1, 2]
    assert results[0] == results[2]
