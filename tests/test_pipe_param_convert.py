"""PipelinedViT ↔ plain ViT parameter converters (models/vit.py).

Train pipelined (MESH.PIPE>1), then evaluate / resume on any topology:
the stacked ``stages`` param scatters to ``Block_i`` and back, weights
identical, logits identical.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distribuuuu_tpu import models
from distribuuuu_tpu.models.vit import flat_to_pipe_params, pipe_to_flat_params

HP = dict(num_classes=10, dtype=jnp.float32, patch=8, dim=32, depth=4,
          num_heads=2)


@pytest.mark.slow
def test_pipe_params_load_into_flat_vit():
    pipe = models.build_model("vit_tiny", pipe_stages=2, **HP)
    flat = models.build_model("vit_tiny", **HP)
    x = jnp.asarray(
        np.random.default_rng(0).standard_normal((2, 32, 32, 3)), jnp.float32
    )
    vs = jax.tree.map(np.asarray, pipe.init(jax.random.key(0), x, train=False))
    want = pipe.apply(vs, x, train=False)
    got = flat.apply(
        {"params": pipe_to_flat_params(vs["params"])}, x, train=False
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_partition_metadata_stays_consistent():
    """Boxed trees convert with valid metadata in BOTH directions: the
    'pipe' axis name travels with the stage dim (dropped on scatter,
    prepended on stack), so sharding derivation (nn.get_partition_spec /
    tp.param_shardings) works on converted trees — ranks always match."""
    import flax.linen as nn

    pipe = models.build_model("vit_tiny", pipe_stages=2, **HP)
    flat = models.build_model("vit_tiny", **HP)
    x = jnp.ones((1, 32, 32, 3), jnp.float32)

    boxed_pipe = pipe.init(jax.random.key(0), x, train=False)["params"]
    flat_conv = pipe_to_flat_params(boxed_pipe)
    for path, leaf in jax.tree_util.tree_leaves_with_path(
        flat_conv, is_leaf=lambda n: isinstance(n, nn.Partitioned)
    ):
        if isinstance(leaf, nn.Partitioned):
            assert len(leaf.names) == leaf.value.ndim, path
    nn.get_partition_spec(flat_conv)  # must not raise

    boxed_flat = flat.init(jax.random.key(1), x, train=False)["params"]
    pipe_conv = flat_to_pipe_params(boxed_flat, 2)
    for path, leaf in jax.tree_util.tree_leaves_with_path(
        pipe_conv["stages"], is_leaf=lambda n: isinstance(n, nn.Partitioned)
    ):
        assert isinstance(leaf, nn.Partitioned), path
        assert leaf.names[0] == "pipe", path
        assert len(leaf.names) == leaf.value.ndim, path
    nn.get_partition_spec(pipe_conv)  # must not raise


def test_flat_to_pipe_roundtrip_identity():
    flat = models.build_model("vit_tiny", **HP)
    x = jnp.ones((1, 32, 32, 3), jnp.float32)
    params = jax.tree.map(
        np.asarray, flat.init(jax.random.key(1), x, train=False)["params"]
    )
    back = pipe_to_flat_params(flat_to_pipe_params(params, 2))
    for (ka, a), (kb, b) in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves_with_path(back),
    ):
        assert jax.tree_util.keystr(ka) == jax.tree_util.keystr(kb)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pipe_model_runs_with_converted_flat_params():
    """The other direction: a plain ViT checkpoint loads into the
    pipelined model (sequential fallback path — no pipe mesh here)."""
    flat = models.build_model("vit_tiny", **HP)
    pipe = models.build_model("vit_tiny", pipe_stages=2, **HP)
    x = jnp.asarray(
        np.random.default_rng(2).standard_normal((2, 32, 32, 3)), jnp.float32
    )
    params = jax.tree.map(
        np.asarray, flat.init(jax.random.key(2), x, train=False)["params"]
    )
    want = flat.apply({"params": params}, x, train=False)
    got = pipe.apply(
        {"params": flat_to_pipe_params(params, 2)}, x, train=False
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
