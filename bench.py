"""Benchmark: ResNet-50 training throughput (images/sec/chip) on real hardware.

Runs the framework's actual jitted train step (fwd + CE + bwd + SGD-nesterov
update + in-graph metrics, bf16 compute / fp32 params) on synthetic ImageNet
shapes, steady-state, on however many chips are attached, and prints ONE JSON
line. Also times the EVAL step (``build_eval_workload`` — the forward
test_model and the serving engine run); its ``eval_images_per_sec_per_chip``
is the per-replica serving throughput ceiling.

``vs_baseline``: the reference publishes no throughput numbers
(SURVEY.md §6), so the denominator is the widely-reproduced ~400 img/s/GPU
that torch DDP ResNet-50 fp32 achieves on the reference's A100-class hardware
(README.md:183) — the setup its published baselines were trained with.
"""

from __future__ import annotations

import json
import os
import sys
import time

BASELINE_IMG_PER_SEC_PER_CHIP = 400.0  # A100 fp32 DDP resnet50 (see docstring)

# ResNet-50 @224²: 4.09 GMACs fwd (torchvision count) × 2 FLOPs/MAC ≈ 8.2
# GFLOP; fwd+bwd ≈ 3× fwd. Convention: FLOPs = 2·MACs (the standard MFU
# convention — see PERF.md "Where the time goes" for the derivation).
# Since r10 this hand constant is the CROSS-CHECK, not the source: the
# mfu field comes from XLA's own cost_analysis of the step program
# (telemetry/costmodel.py); the bench warns and records flops_drift_pct
# when the two disagree by more than DRIFT_WARN_PCT — the signal that
# this table rotted as the model changed.
RESNET50_TRAIN_FLOPS_PER_IMG = 3 * 2 * 4.09e9
DRIFT_WARN_PCT = 5.0

# Peak dense bf16 FLOP/s by device kind: ONE table for the whole repo,
# owned by telemetry/costmodel.py (DEVICE_PEAKS — adds HBM bandwidth and
# capacity columns for the roofline/headroom ledger). PEAK_BF16 keeps
# the historical name/shape for existing callers.
from distribuuuu_tpu.telemetry import costmodel  # noqa: E402

PEAK_BF16 = {
    kind: entry["flops"]
    for kind, entry in costmodel.DEVICE_PEAKS.items()
    if kind != "cpu"  # nominal CPU peak is for off-chip roofline tests
}


def build_workload(fold: int = 4, per_chip_batch: int = 128):
    """Build the bench's compiled+warmed train step.

    Returns ``(window, meta)`` — ``window(iters)`` runs ``iters`` calls
    (``fold`` optimizer steps each) and returns elapsed seconds, fenced on
    a value fetch; ``meta`` has batch geometry. Factored out so
    ``tools/ab_bench.py`` can build the SAME workload under two different
    trace-time environments and interleave paired timing windows.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    import distribuuuu_tpu.config as config
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.config import cfg
    from distribuuuu_tpu.parallel import mesh as mesh_lib, sharding as sharding_lib
    from distribuuuu_tpu.utils.optim import construct_optimizer

    config.reset_cfg()
    # DISTRIBUUUU_BENCH_ARCH: run the same harness on another zoo arch
    # (ab_bench env plumbing reaches this at build time) — e.g. the
    # regnety_160 grouped-conv A/Bs (PERF.md r5).
    cfg.MODEL.ARCH = os.environ.get("DISTRIBUUUU_BENCH_ARCH", "resnet50")
    # DISTRIBUUUU_REMAT=1: TRAIN.REMAT (stage 1-2 rematerialization) for
    # the remat-for-traffic A/B — `tools/ab_bench.py --preset remat`.
    if os.environ.get("DISTRIBUUUU_REMAT", "") not in ("", "0"):
        cfg.TRAIN.REMAT = True
    cfg.MODEL.NUM_CLASSES = 1000
    n_chips = len(jax.devices())
    batch = per_chip_batch * n_chips

    mesh = mesh_lib.build_mesh()
    model = trainer.build_model_from_cfg()
    state = trainer.create_train_state(model, jax.random.key(0), mesh, 224)
    optimizer = construct_optimizer()
    train_step = trainer.make_scan_train_step(model, optimizer, topk=5, fold=fold)

    # DISTRIBUUUU_XLA_OPTS="k=v;k=v": per-variant XLA compiler options for
    # the flag-sweep experiments (tools/xla_flag_sweep.py). An outer jit
    # re-wrap — the inner jit inlines during tracing, so the options govern
    # the whole step compilation.
    xla_opts = os.environ.get("DISTRIBUUUU_XLA_OPTS", "")
    if xla_opts:
        copts = {}
        for p in xla_opts.split(";"):
            if not p:
                continue
            if "=" not in p:
                # a silently-dropped flag would make a sweep report ~1.00×
                # for an option that was never applied
                raise ValueError(
                    f"DISTRIBUUUU_XLA_OPTS entry {p!r} is not k=v"
                )
            k, v = p.split("=", 1)
            copts[k] = v
        train_step = jax.jit(
            train_step, donate_argnums=0, compiler_options=copts
        )

    rng = np.random.default_rng(0)
    host_batch = {
        "image": rng.standard_normal(
            (fold, batch, 224, 224, 3)
        ).astype(np.float32),
        "label": rng.integers(0, 1000, size=(fold, batch)).astype(np.int32),
        "mask": np.ones((fold, batch), np.float32),
    }
    gbatch = sharding_lib.shard_stacked_batch(mesh, host_batch)

    # The timed window must end with a *value fetch* that depends on the last
    # step's parameter update: on remote-tunnel transports (axon)
    # block_until_ready was observed returning before the work ran (a
    # 8192³ matmul "finished" at 100+ PFLOP/s), so syncing on a scalar
    # derived from the updated params is the reliable fence.
    def fence(state):
        leaf = jax.tree.leaves(state.params)[0]
        return float(jnp.sum(leaf))

    box = {"state": state}

    def window(iters: int) -> float:
        st = box["state"]
        t0 = time.perf_counter()
        for _ in range(iters):
            st, _metrics = train_step(st, gbatch)
        fence(st)
        dt = time.perf_counter() - t0
        box["state"] = st
        return dt

    # XLA cost-model ledger of this workload (lowering only re-traces —
    # no extra compile): the measured flops the mfu field is sourced
    # from, extracted BEFORE the warmup donates the state buffers. The
    # probe is a PER-STEP program, not the folded one — XLA cost
    # analysis counts a lax.scan body once regardless of trip count, so
    # the folded program cannot source per-step flops
    # (telemetry/costmodel.py has the same rule). ``cost`` is per step
    # of ``batch`` images; None when the backend omits cost keys —
    # main() falls back to the hand table, flagged analytic.
    cost = None
    try:
        probe_step = trainer.make_train_step(model, optimizer, topk=5)
        single = jax.tree.map(lambda x: x[0], gbatch)  # one (batch,...) step
        cost = costmodel.normalize_cost(
            probe_step.lower(box["state"], single).cost_analysis()
        )
    except Exception:
        cost = None

    # compile + warmup
    window(1)
    window(3)

    meta = {
        "n_chips": n_chips,
        "batch": batch,
        "fold": fold,
        "per_chip_batch": per_chip_batch,
        "device_kind": jax.devices()[0].device_kind,
        "cost": cost,  # ONE optimizer step of `batch` images (see above)
    }
    return window, meta


def build_eval_workload(per_chip_batch: int = 128):
    """Compiled+warmed EVAL step (trainer.make_eval_step — the exact
    forward validate()/test_model() and the serving engine run).

    The resulting img/s/chip is the serving engine's single-batch
    ceiling: one replica cannot exceed it at full batch occupancy
    (tools/serve_bench.py measures how close dynamic batching gets).
    Same ``window(iters) -> seconds`` contract as ``build_workload``.
    """
    import jax
    import numpy as np

    import distribuuuu_tpu.config as config
    from distribuuuu_tpu import trainer
    from distribuuuu_tpu.config import cfg
    from distribuuuu_tpu.parallel import mesh as mesh_lib, sharding as sharding_lib

    config.reset_cfg()
    cfg.MODEL.ARCH = os.environ.get("DISTRIBUUUU_BENCH_ARCH", "resnet50")
    cfg.MODEL.NUM_CLASSES = 1000
    n_chips = len(jax.devices())
    batch = per_chip_batch * n_chips

    mesh = mesh_lib.build_mesh()
    model = trainer.build_model_from_cfg()
    state = trainer.create_train_state(model, jax.random.key(0), mesh, 224)
    eval_step = trainer.make_eval_step(model, topk=5)

    rng = np.random.default_rng(0)
    gbatch = sharding_lib.shard_batch(mesh, {
        "image": rng.standard_normal((batch, 224, 224, 3)).astype(np.float32),
        "label": rng.integers(0, 1000, size=(batch,)).astype(np.int32),
        "mask": np.ones((batch,), np.float32),
    })

    def window(iters: int) -> float:
        t0 = time.perf_counter()
        m = None
        for _ in range(iters):
            m = eval_step(state, gbatch)
        # value fetch of the last step's metrics = the dispatch fence
        # (same reliable-sync rationale as the train window)
        float(m["loss_sum"])
        return time.perf_counter() - t0

    window(1)
    window(3)
    meta = {"n_chips": n_chips, "batch": batch,
            "per_chip_batch": per_chip_batch}
    return window, meta


def main():
    import jax

    # The framework's folded dispatch mode (≙ TRAIN.STEPS_PER_CALL in the
    # trainer): FOLD optimizer steps per compiled call via lax.scan,
    # removing the per-step host dispatch (~4 ms on tunneled transports)
    # from the critical path. Same train-step math.
    window, meta = build_workload(fold=4, per_chip_batch=128)
    n_chips, batch, fold = meta["n_chips"], meta["batch"], meta["fold"]
    per_chip_batch = meta["per_chip_batch"]

    # timed steady state — best of three windows (tunnel jitter is ±3%)
    iters = 10  # calls; fold steps each
    dt = min(window(iters) for _ in range(3))

    img_per_sec = batch * fold * iters / dt
    img_per_sec_per_chip = img_per_sec / n_chips
    peak = PEAK_BF16.get(jax.devices()[0].device_kind)
    out = {
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_per_sec_per_chip, 2),
        "unit": "images/sec/chip",
        # bf16-TPU vs the reference's fp32 A100 DDP (the setup its published
        # baselines used; it has no AMP mode) — see module docstring.
        "vs_baseline": round(
            img_per_sec_per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 3
        ),
        "baseline": "A100 fp32 DDP ~400 img/s/GPU (reference has no AMP)",
        "fold": fold,
        "per_chip_batch": per_chip_batch,
    }
    # mfu: measured flops (XLA cost ledger of the very step program the
    # window timed) over the device peak; the hand table is demoted to a
    # cross-check — flops_drift_pct > ±5% means it rotted (satellite:
    # the table no longer silently drifts as models change).
    cost = meta.get("cost")
    flops_per_img = None
    if cost and cost.get("flops"):
        flops_per_img = cost["flops"] / batch  # cost is per step (meta)
        out["flops_per_img"] = round(flops_per_img, 1)
        out["mfu_source"] = "xla"
        if os.environ.get("DISTRIBUUUU_BENCH_ARCH", "resnet50") == "resnet50":
            drift = costmodel.drift_pct(
                flops_per_img, RESNET50_TRAIN_FLOPS_PER_IMG
            )
            out["flops_drift_pct"] = round(drift, 2)
            if abs(drift) > DRIFT_WARN_PCT:
                print(
                    f"# WARNING: hand FLOP table drifted {drift:+.1f}% from "
                    f"the XLA cost model ({flops_per_img / 1e9:.2f} vs "
                    f"{RESNET50_TRAIN_FLOPS_PER_IMG / 1e9:.2f} GFLOP/img) — "
                    "update RESNET50_TRAIN_FLOPS_PER_IMG",
                    file=sys.stderr,
                )
    elif os.environ.get("DISTRIBUUUU_BENCH_ARCH", "resnet50") == "resnet50":
        # backend omitted cost keys: analytic fallback, flagged
        flops_per_img = RESNET50_TRAIN_FLOPS_PER_IMG
        out["mfu_source"] = "analytic"
    if peak and flops_per_img:
        out["mfu"] = round(img_per_sec_per_chip * flops_per_img / peak, 4)

    # eval path (VERDICT r5 item 5): the inference forward test_model and
    # the serving engine run — its img/s/chip is serving's per-replica
    # throughput ceiling (PERF.md zoo table, eval column).
    eval_window, eval_meta = build_eval_workload(per_chip_batch=128)
    eval_iters = 10
    eval_dt = min(eval_window(eval_iters) for _ in range(3))
    eval_img_per_sec = eval_meta["batch"] * eval_iters / eval_dt
    out["eval_images_per_sec_per_chip"] = round(
        eval_img_per_sec / eval_meta["n_chips"], 2
    )
    out["eval_batch_ms"] = round(
        eval_dt / eval_iters * 1e3, 2
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
