#!/usr/bin/env bash
# Static checks (≙ the reference's isort → black → flake8 pipeline,
# ref: /root/reference/.dev/pre-commit.sh). Formatters/linters run when
# installed; the compile + test-collection floor always runs, so the hook is
# useful even on hermetic machines with no lint toolchain.
#
# Install as a git hook:  ln -s ../../.dev/pre-commit.sh .git/hooks/pre-commit
set -euo pipefail
cd "$(dirname "$0")/.."

PY_TARGETS=(distribuuuu_tpu tests tutorial train_net.py test_net.py bench.py)

if command -v ruff >/dev/null 2>&1; then
    echo "[pre-commit] ruff check"
    ruff check "${PY_TARGETS[@]}"
    echo "[pre-commit] ruff format --check"
    ruff format --check "${PY_TARGETS[@]}"
else
    if command -v isort >/dev/null 2>&1; then
        echo "[pre-commit] isort --check"
        isort --check-only --profile black "${PY_TARGETS[@]}"
    fi
    if command -v black >/dev/null 2>&1; then
        echo "[pre-commit] black --check"
        black --check "${PY_TARGETS[@]}"
    fi
    if command -v flake8 >/dev/null 2>&1; then
        echo "[pre-commit] flake8"
        flake8 "${PY_TARGETS[@]}"
    fi
fi

echo "[pre-commit] compileall (syntax floor)"
python -m compileall -q distribuuuu_tpu tests tutorial train_net.py test_net.py bench.py

echo "[pre-commit] pytest collection (import floor)"
python -m pytest tests/ -q --collect-only >/dev/null

echo "[pre-commit] ok"
